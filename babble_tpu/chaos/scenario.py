"""Scenario runner: execute a FaultPlan against a real node cluster.

Two execution modes over the same plan:

**Deterministic in-memory cluster** (:class:`ScenarioRunner`) — full
Node objects (gossip protocol, core lock, commit queue, fast-forward
path) over ``InmemNetwork`` transports wrapped in ``FaultyTransport``,
driven *sequentially*: the runner owns the only source of initiative
(one gossip exchange per step, consensus on an explicit cadence), node
select-loops run with heartbeats off and exist purely to serve inbound
RPCs and drain commits.  Combined with seed-derived identities
(:func:`~babble_tpu.crypto.keys.key_from_scalar`), deterministic ECDSA
nonces, a seeded logical event clock and the injector's per-link RNG
streams, two runs of the same (scenario, seed) produce bit-identical
fault schedules AND bit-identical committed orders — the property the
acceptance tests fingerprint.

**Live fleet** (:func:`run_live`) — a ``testnet.TestnetRunner``
subprocess fleet where every node self-injects faults from the same
(plan, seed) via ``babble-tpu run --chaos_plan`` (cli.py wraps the TCP
transport in a FaultyTransport), the runner drives crash/restart from
the plan's schedule against wall-clock ticks, and the report is a
fleet-wide /Stats + /metrics sweep (``babble_chaos_faults_total``
distinguishes injected faults from organic ones).  Wall-clock fleets
are not bit-reproducible — the *fault schedule* still is, per link.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import random
import shutil
import tempfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..crypto.keys import P256_ORDER, KeyPair, key_from_scalar, sha256
from ..net.inmem_transport import InmemNetwork
from ..net.peers import Peer
from ..node.config import Config
from ..node.node import Node
from ..proxy.inmem import InmemAppProxy
from .disk import apply_disk_faults
from .injector import FaultInjector
from .invariants import InvariantChecker, InvariantReport
from .plan import ByzantineSpec, Scenario, crash_schedule
from .transport import FaultyTransport


def deterministic_keys(seed: int, n: int) -> List[KeyPair]:
    """n keypairs derived from the seed, sorted by pub hex so list
    index == canonical participant id."""
    keys = []
    for i in range(n):
        digest = sha256(f"babble-chaos-key:{seed}:{i}".encode())
        d = int.from_bytes(digest, "big") % (P256_ORDER - 1) + 1
        keys.append(key_from_scalar(d))
    return sorted(keys, key=lambda k: k.pub_hex)


def joiner_keys(seed: int, n: int) -> List[KeyPair]:
    """Keypairs for mid-run joiners (membership plane) — a SEPARATE
    derivation stream, unsorted: joiner ids are assigned by consensus
    (append order at each epoch boundary), not by pub-hex rank."""
    keys = []
    for i in range(n):
        digest = sha256(f"babble-chaos-joiner:{seed}:{i}".encode())
        d = int.from_bytes(digest, "big") % (P256_ORDER - 1) + 1
        keys.append(key_from_scalar(d))
    return keys


@dataclass
class ScenarioResult:
    """Everything a scenario run observed, in JSON-able form."""

    name: str
    seed: int
    steps: int
    fault_schedule: List[tuple] = field(default_factory=list)
    fault_counts: Dict[str, int] = field(default_factory=dict)
    committed: Dict[int, List[str]] = field(default_factory=dict)
    consensus: Dict[int, List[str]] = field(default_factory=dict)
    submitted: List[str] = field(default_factory=list)
    honest: List[int] = field(default_factory=list)
    restarted: Set[int] = field(default_factory=set)
    alive: Set[int] = field(default_factory=set)
    heal_tick: Optional[int] = None
    consensus_counts_at_heal: Dict[int, int] = field(default_factory=dict)
    consensus_counts_at_bound: Dict[int, int] = field(default_factory=dict)
    consensus_counts_final: Dict[int, int] = field(default_factory=dict)
    fork_detected: Dict[int, bool] = field(default_factory=dict)
    fast_forwards: Dict[int, int] = field(default_factory=dict)
    fork_attack: Optional[dict] = None
    #: per-creator eviction observations (ISSUE 8): for every crashed
    #: creator, the highest eviction-horizon index a surviving node
    #: recorded for it during the outage (-1 = its tail never evicted)
    eviction_horizons: Dict[int, int] = field(default_factory=dict)
    #: max live-window slot count observed on survivors while any node
    #: was down — the memory-bounded half of eviction_advanced
    outage_live_window_max: int = 0
    #: fast-forward snapshots each node refused on proof failure
    #: (babble_ff_proof_rejects_total at run end)
    ff_proof_rejects: Dict[int, int] = field(default_factory=dict)
    #: membership plane: per-node final epoch and membership ledger
    #: ((epoch, kind, pub, boundary) tuples — the epoch_agreement
    #: invariant requires them identical on every honest node)
    epochs: Dict[int, int] = field(default_factory=dict)
    membership_logs: Dict[int, list] = field(default_factory=dict)
    #: scenario indices that joined mid-run (prefix agreement treats
    #: them like restarts: their log starts mid-stream)
    joined: Set[int] = field(default_factory=set)
    #: committed logs of the drift-free twin run (skew_robust_order)
    noskew_committed: Optional[Dict[int, List[str]]] = None
    #: per-node committed tx -> (round_received, consensus_ts) keys of
    #: the drift-free twin — the strict-order baseline drift must not
    #: permute ((rr, cts)-TIED commits fall to the whitened-signature
    #: tiebreak, which legitimately differs between runs because the
    #: drifted timestamps are inside the signed bodies)
    noskew_keys: Optional[Dict[int, dict]] = None
    #: this run's own committed-key map (kept so a run can serve as a
    #: twin)
    committed_keys: Dict[int, dict] = field(default_factory=dict)
    #: per-node flight-recorder dumps (ISSUE 11): captured at each
    #: crash and at run end, so an invariant violation ships its own
    #: last-N-transitions post-mortem instead of demanding a re-run.
    #: Embedded in to_dict() only when the report has violations.
    flight_dumps: Dict[int, list] = field(default_factory=dict)
    report: Optional[InvariantReport] = None

    def fingerprint(self) -> str:
        """SHA-256 over the canonical fault schedule + every node's
        committed/consensus order — identical across runs iff the run
        was bit-for-bit reproduced."""
        payload = json.dumps({
            "schedule": [list(t) for t in self.fault_schedule],
            "committed": {str(k): v for k, v in sorted(self.committed.items())},
            "consensus": {str(k): v for k, v in sorted(self.consensus.items())},
        }, sort_keys=True).encode()
        return hashlib.sha256(payload).hexdigest()

    def to_dict(self) -> dict:
        return {
            "name": self.name, "seed": self.seed, "steps": self.steps,
            "fingerprint": self.fingerprint(),
            "fault_counts": dict(self.fault_counts),
            "fault_schedule": [list(t) for t in self.fault_schedule],
            "committed": {str(k): v for k, v in sorted(self.committed.items())},
            "submitted": list(self.submitted),
            "honest": list(self.honest),
            "restarted": sorted(self.restarted),
            "alive": sorted(self.alive),
            "heal_tick": self.heal_tick,
            "consensus_counts": {
                str(k): v
                for k, v in sorted(self.consensus_counts_final.items())
            },
            "fork_detected": {
                str(k): v for k, v in sorted(self.fork_detected.items())
            },
            "fast_forwards": {
                str(k): v for k, v in sorted(self.fast_forwards.items())
            },
            "fork_attack": self.fork_attack,
            "eviction_horizons": {
                str(k): v for k, v in sorted(self.eviction_horizons.items())
            },
            "outage_live_window_max": self.outage_live_window_max,
            "ff_proof_rejects": {
                str(k): v for k, v in sorted(self.ff_proof_rejects.items())
            },
            "epochs": {str(k): v for k, v in sorted(self.epochs.items())},
            "membership_logs": {
                str(k): [list(t) for t in v]
                for k, v in sorted(self.membership_logs.items())
            },
            "joined": sorted(self.joined),
            "invariants": self.report.to_dict() if self.report else None,
            # post-mortem artifact: the per-node flight narratives ride
            # the FAILURE (chaos run --json), never a green run's JSON
            **({"flight": {str(k): v
                           for k, v in sorted(self.flight_dumps.items())}}
               if self.report is not None and not self.report.ok
               and self.flight_dumps else {}),
        }


@dataclass
class _Handle:
    idx: int
    addr: str
    key: KeyPair
    node: Optional[Node] = None
    proxy: Optional[InmemAppProxy] = None
    alive: bool = True
    saved_engine: object = None
    engine_at_restart: object = None
    restarted: bool = False


class ScenarioRunner:
    """Deterministic in-memory execution of one scenario."""

    def __init__(self, scenario: Scenario, seed: Optional[int] = None,
                 consensus_every: int = 6, kernel_class: str = "auto",
                 diet: bool = True, _twin: bool = False):
        #: this run IS a drift-free twin (skew_robust_order): collect
        #: committed keys, never recurse into another twin
        self._twin = _twin
        self.scenario = scenario
        #: compiled-surface pin for the fused engine (node/config.py):
        #: the incremental-vs-full parity suite runs the same scenario
        #: under "latency" and "throughput" and asserts bit-identical
        #: fingerprints
        self.kernel_class = kernel_class
        #: kernel working-set diet pin (ROADMAP item 4): False runs the
        #: pre-diet kernels (f32 vote tallies, full-height fd scans) —
        #: the fingerprint-parity suite runs both and asserts identity
        self.diet = diet
        self.seed = scenario.seed if seed is None else seed
        self.consensus_every = consensus_every

    def run(self) -> ScenarioResult:
        result = asyncio.run(self._run())
        sc = self.scenario
        byz = sc.plan.byzantine
        lying = byz is not None and byz.mode == "lying_ts"
        if ((sc.plan.clock_skew is not None or lying)
                and "skew_robust_order" in sc.invariants):
            # the invariant is a differential claim: the same (scenario,
            # seed) with adversarial time OFF — clock drift removed,
            # the lying_ts actor made honest — must commit the same
            # strict (rr, cts) order.  Median timestamps absorb bounded
            # per-creator skew, and the insert-time clamp pins a lying
            # minority's claims into the honest envelope.  Run the
            # honest-time twin and re-check.
            d = sc.to_dict()
            d["plan"].pop("clock_skew", None)
            if lying:
                d["plan"].pop("byzantine", None)
            d["invariants"] = [
                i for i in d["invariants"] if i != "skew_robust_order"
            ]
            from .plan import Scenario as _Scenario

            twin = ScenarioRunner(
                _Scenario.from_dict(d), seed=self.seed,
                consensus_every=self.consensus_every,
                kernel_class=self.kernel_class, diet=self.diet,
                _twin=True,
            ).run()
            result.noskew_committed = dict(twin.committed)
            result.noskew_keys = dict(twin.committed_keys)
            result.report = InvariantChecker().check(sc, result)
        return result

    async def _membership_op(self, op, handles, boot, injector,
                             result, n_founders: int) -> None:
        """Execute one scheduled churn verb: boot the joiner (observer)
        and submit its signed join tx, or submit a leave tx signed by
        the departing key — both through an ordinary live node's pool,
        because membership transitions ARE transactions.  The subject's
        key signs either way (the runner holds every scenario key, so
        leave-mid-outage works even while the leaver is down)."""
        from ..membership.transition import build_membership_tx

        h = handles[op.node]
        if op.kind == "join" and h.node is None:
            boot(h)
            result.joined.add(op.node)
        via = None
        if op.via is not None and handles[op.via].alive:
            via = handles[op.via]
        if via is None:
            via = next(
                (x for x in handles
                 if x.alive and x.idx != op.node and x.idx < n_founders),
                None,
            )
        if via is None:
            return   # nobody alive to carry the transition
        epoch = int(getattr(via.node.core.hg, "epoch", 0))
        tx = build_membership_tx(op.kind, h.key, h.addr, epoch)
        async with via.node.core_lock:
            via.node.transaction_pool.append(tx)
        injector.record(op.kind, op.node, via.idx, epoch=epoch)

    # ------------------------------------------------------------------

    async def _run(self) -> ScenarioResult:
        sc = self.scenario
        n = sc.nodes
        seed = self.seed
        injector = FaultInjector(sc.plan, seed,
                                 tick_seconds=sc.tick_seconds)
        rng = random.Random(f"babble-chaos-scenario:{seed}")
        # logical event clock: strictly increasing ns, identical across
        # runs because every event creation happens inside one of the
        # runner's sequential awaits
        tick_ns = {"t": 1_700_000_000_000_000_000}

        def clock() -> int:
            tick_ns["t"] += 1_000_000
            return tick_ns["t"]

        # membership plane: founders get canonical ids (sorted keys);
        # joiner identities come from a separate stream and take the
        # scenario indices past the founding set
        total = n + sc.joiners
        keys = deterministic_keys(seed, n) + joiner_keys(seed, sc.joiners)
        addrs = [f"inmem://chaos{i}" for i in range(total)]
        addr_index = {a: i for i, a in enumerate(addrs)}
        peers = [
            Peer(net_addr=addrs[i], pub_key_hex=keys[i].pub_hex)
            for i in range(n)
        ]
        net = InmemNetwork()
        handles = [
            _Handle(idx=i, addr=addrs[i], key=keys[i],
                    alive=(i < n))
            for i in range(total)
        ]

        # Honest crash scenarios run DURABLY: each node writes a real
        # on-disk WAL (fsync=off — in-process durability, the tier-1
        # fast path) plus optional periodic checkpoints, a crash drops
        # the live engine on the floor, and the restart recovers
        # through the real ladder (checkpoint -> WAL replay -> seq
        # probe -> gossip/fast-forward).  This is what lets crash
        # scenarios run honest-mode: recovery is seq-exact, so a
        # restarted node never re-mints a published index and no peer
        # ever reads it as an equivocator.  (Byzantine-engine crashes
        # keep the legacy keep-the-engine model: fork-aware restarts
        # are exercised by the live tier.)
        durable = (sc.engine == "fused"
                   and bool(sc.plan.crashes or sc.plan.disk))
        durable_root = (
            tempfile.mkdtemp(prefix="babble-chaos-durable-")
            if durable else None
        )

        def ckpt_dir(i: int) -> str:
            return os.path.join(durable_root, f"node{i}", "ckpt")

        def make_conf(i: int) -> Config:
            conf = Config.test_config(heartbeat=1.0)
            conf.cache_size = sc.cache_size
            conf.seq_window = sc.seq_window
            if sc.inactive_rounds is not None:
                conf.inactive_rounds = sc.inactive_rounds
            conf.kernel_class = self.kernel_class
            conf.packed_votes = self.diet
            conf.frontier = self.diet
            conf.byzantine = (sc.engine == "byzantine")
            # flight stays ON (invariant violations attach its dumps);
            # lineage OFF — nothing scrapes /debug/lineage in the
            # in-memory runner, and its per-insert/ship records are
            # pure overhead on the scenario hot loop
            conf.lineage = False
            # anchor collection OFF: its background RPC rounds would
            # cross partitions at timing-dependent moments and perturb
            # the recorded fault schedule (the node-level anchor tests
            # own this path; live fleets keep the default interval)
            conf.anchor_interval = 0
            # positive interval with gossip=False means: syncs only mark
            # the pipeline dirty and the RUNNER decides when consensus
            # runs (a timer task would reintroduce wall-clock
            # nondeterminism) — see _maybe_consensus
            conf.consensus_interval = 1e9
            if durable:
                conf.wal_dir = os.path.join(durable_root, f"node{i}", "wal")
                conf.wal_fsync = "off"
            return conf

        def boot(h: _Handle, engine=None) -> None:
            inner = net.transport(h.addr)
            transport = FaultyTransport(
                inner, injector, h.idx, addr_index,
                forge_key=(h.key if injector.is_snapshot_forger(h.idx)
                           else None),
            )
            h.proxy = InmemAppProxy()
            conf = make_conf(h.idx)
            node_peers = peers
            if h.idx >= n:
                # joiner: the founders are its consensus bootstrap set;
                # its own address rides only the address book (it is an
                # observer until its join tx's epoch boundary)
                conf.bootstrap_peers = list(peers)
                node_peers = peers + [
                    Peer(net_addr=h.addr, pub_key_hex=h.key.pub_hex)
                ]
            h.node = Node(conf, h.key, node_peers, transport,
                          h.proxy, engine=engine)
            # adversarial time (ROADMAP 5 first slice): a per-node
            # bounded drift offset from the injector's seeded stream
            # rides on the shared logical clock through the Core.now_ns
            # hook — event bodies stay deterministic per (seed, node)
            drift = injector.clock_drift_ns(h.idx)
            if injector.is_ts_liar(h.idx):
                # the lying_ts byzantine actor: per-mint EXTREME claimed
                # timestamps from a dedicated seeded stream — the
                # creator-claimed-median attack the insert-time clamp
                # absorbs.  Still deterministic per (seed, node): every
                # mint happens inside one of the runner's sequential
                # awaits.
                h.node.core.now_ns = (
                    lambda d=drift, i=h.idx:
                    clock() + d + injector.lying_ts_offset_ns(i)
                )
            elif drift:
                h.node.core.now_ns = (lambda d=drift: clock() + d)
            else:
                h.node.core.now_ns = clock
            if engine is None:
                # recovery-aware: skipped when WAL replay restored a
                # head, deferred while the seq probe negotiates
                h.node.init()
            h.node.run_task(gossip=False)
            h.alive = True

        for h in handles[:n]:
            boot(h)
        if sc.plan.clock_skew is not None:
            for h in handles[:n]:
                d = injector.clock_drift_ns(h.idx)
                if d:
                    injector.record("clock_skew", h.idx, h.idx,
                                    drift_ns=d)

        byz = sc.plan.byzantine
        honest = [i for i in range(n) if byz is None or byz.node != i]
        result = ScenarioResult(name=sc.name, seed=seed, steps=sc.steps,
                                honest=honest)
        honest.extend(range(n, total))   # joiners are never byzantine
        sched = crash_schedule(sc.plan)
        #: membership churn schedule: tick -> ops (declaration order)
        member_sched: Dict[int, List] = {}
        for op in list(sc.plan.joins) + list(sc.plan.leaves):
            member_sched.setdefault(op.tick, []).append(op)
        heal_ticks = [p.heal for p in sc.plan.partitions
                      if p.heal is not None]
        heal_ticks += [c.restart for c in sc.plan.crashes
                       if c.restart is not None]
        heal_tick = max(heal_ticks) if heal_ticks else None
        result.heal_tick = heal_tick
        submitted = 0
        fork_done = False
        #: deterministic forger encounters: a node restarting under a
        #: forge_snapshot actor gossips AT the forger first, so the
        #: forged-fast-forward path is exercised on every seed instead
        #: of depending on the random peer draw finding the actor
        forced_gossip: List[tuple] = []

        async def gossip_once(a: int, b: int) -> None:
            await handles[a].node._gossip(addrs[b])

        async def sample_counts() -> Dict[int, int]:
            out = {}
            for h in handles:
                if h.alive:
                    out[h.idx] = h.node.core.hg.consensus_events_count()
            return out

        try:
            for step in range(sc.steps):
                injector.advance_to(step)
                for action, node_idx in sched.get(step, ()):
                    h = handles[node_idx]
                    if action == "crash" and h.alive:
                        # the crash IS the interesting transition: grab
                        # the ring before the node object goes away (a
                        # restart builds a fresh recorder).  APPEND —
                        # a second crash of the same node must not
                        # overwrite the first narrative
                        result.flight_dumps[node_idx] = (
                            result.flight_dumps.get(node_idx, [])
                            + h.node.flight.dump()
                        )
                        if durable:
                            # power-cut semantics: drop the file handles
                            # with NO clean-shutdown receipt and discard
                            # the live engine — whatever the WAL (and
                            # any periodic checkpoint) captured is all
                            # the restart gets
                            h.saved_engine = None
                            h.node.core.wal.abort()
                        else:
                            h.saved_engine = h.node.core.hg
                        await h.node.shutdown()
                        h.alive = False
                        injector.record("crash", node_idx, node_idx)
                    elif action == "restart" and not h.alive:
                        if durable:
                            # the real recovery ladder: seeded disk rot
                            # first (that is when fsync lies surface),
                            # then checkpoint -> WAL replay -> probe
                            if sc.plan.disk is not None:
                                # off-loop: the structure-relative
                                # draws decode checkpoint meta
                                def rot(idx=node_idx):
                                    apply_disk_faults(
                                        injector, sc.plan.disk, idx,
                                        ckpt_dir(idx),
                                        os.path.join(durable_root,
                                                     f"node{idx}", "wal"),
                                    )
                                await asyncio.get_running_loop() \
                                    .run_in_executor(None, rot)
                            from ..store import load_checkpoint_tolerant

                            engine, _err = load_checkpoint_tolerant(
                                ckpt_dir(node_idx)
                            ) if os.path.isdir(ckpt_dir(node_idx)) \
                                else (None, None)
                            boot(h, engine=engine)
                        else:
                            # byzantine crashes restart from the engine
                            # held at crash time (the fork-aware
                            # checkpoint-restored-process model)
                            boot(h, engine=h.saved_engine)
                        h.engine_at_restart = h.node.core.hg
                        h.restarted = True
                        result.restarted.add(node_idx)
                        injector.record("restart", node_idx, node_idx)
                        if (byz is not None
                                and byz.mode == "forge_snapshot"
                                and byz.node != node_idx):
                            forced_gossip.append((node_idx, byz.node))
                if (durable and sc.checkpoint_every > 0
                        and step % sc.checkpoint_every
                        == sc.checkpoint_every - 1):
                    for h in handles:
                        if h.alive:
                            await h.node.save_checkpoint(ckpt_dir(h.idx))
                for op in member_sched.get(step, ()):
                    await self._membership_op(
                        op, handles, boot, injector, result, n
                    )
                if heal_tick is not None and step == heal_tick:
                    result.consensus_counts_at_heal = await sample_counts()
                if (heal_tick is not None
                        and step == heal_tick + sc.liveness_bound):
                    result.consensus_counts_at_bound = await sample_counts()

                if (submitted < sc.txs and sc.tx_every > 0
                        and step % sc.tx_every == 0):
                    live = [h for h in handles if h.alive]
                    target = rng.choice(live)
                    payload = (
                        f"chaos-tx-{submitted}-"
                        f"{rng.getrandbits(32):08x}".encode()
                    )
                    async with target.node.core_lock:
                        target.node.transaction_pool.append(payload)
                    result.submitted.append(payload.hex())
                    submitted += 1

                if (byz is not None and byz.mode == "fork"
                        and not fork_done and step >= byz.at):
                    attack = await self._inject_fork(
                        handles, byz, rng, clock, injector
                    )
                    if attack.get("deferred"):
                        # the branch's self-parent hasn't reached two
                        # honest peers yet — a fork nobody can insert
                        # proves nothing; retry next step
                        pass
                    else:
                        result.fork_attack = attack
                        fork_done = True

                live_idx = [h.idx for h in handles if h.alive]
                # the dialable universe: founders plus joiners that have
                # BOOTED (a joiner's address exists only from its join
                # tick on).  Identical to range(n) for churn-free
                # scenarios, so their draws — and fingerprints — are
                # untouched.
                uni = n + sum(
                    1 for h in handles[n:] if h.node is not None
                )
                if (forced_gossip and handles[forced_gossip[0][0]].alive
                        and handles[forced_gossip[0][1]].alive):
                    a, b = forced_gossip.pop(0)
                    await gossip_once(a, b)
                elif len(live_idx) >= 2:
                    a = rng.choice(live_idx)
                    # deliberate: the target draw includes crashed nodes
                    # — a real peer selector dials from peers.json with
                    # no liveness oracle, so the fleet keeps paying the
                    # dial-a-dead-peer failure exactly like production
                    b = rng.choice([i for i in range(uni) if i != a])
                    await gossip_once(a, b)

                # silent-peer observations (eviction_advanced): while
                # any node is down, sample the survivors' live-window
                # size and any eviction horizon recorded for the dead
                # creators — host mirrors only, no device sync
                down = [h.idx for h in handles if not h.alive]
                if down:
                    for h in handles:
                        if not h.alive:
                            continue
                        snap = h.node.core.hg.stats_snapshot()
                        result.outage_live_window_max = max(
                            result.outage_live_window_max,
                            int(snap.get("live_window", 0)),
                        )
                        heads = getattr(
                            h.node.core.hg.dag, "evicted_heads", {}
                        )
                        for d in down:
                            horizon = heads.get(d)
                            if horizon is not None:
                                result.eviction_horizons[d] = max(
                                    result.eviction_horizons.get(d, -1),
                                    horizon[0],
                                )

                if step % self.consensus_every == self.consensus_every - 1:
                    await self._consensus_pass(handles)

            # settle: the network behaves, everyone reconciles — the
            # phase that makes convergence invariants meaningful
            injector.advance_to(sc.steps)
            injector.quiesce = True
            for _ in range(sc.settle_rounds):
                for a in range(total):
                    if not handles[a].alive:
                        continue
                    for b in range(total):
                        if b != a and handles[b].alive:
                            await gossip_once(a, b)
                await self._consensus_pass(handles)
            await self._consensus_pass(handles, force=True)
            await self._drain_commits(handles)

            result.consensus_counts_final = await sample_counts()
            if heal_tick is not None and not result.consensus_counts_at_bound:
                result.consensus_counts_at_bound = dict(
                    result.consensus_counts_final
                )
            for h in handles:
                if not h.alive:
                    continue
                result.alive.add(h.idx)
                result.committed[h.idx] = [
                    tx.hex() for tx in h.proxy.committed_transactions()
                ]
                result.consensus[h.idx] = list(
                    h.node.core.hg.consensus_events()
                )
                if sc.plan.clock_skew is not None or self._twin:
                    # committed (rr, cts) keys for skew_robust_order:
                    # read from the retained window (these scenarios
                    # never evict it)
                    dag = h.node.core.hg.dag
                    keys: Dict[str, tuple] = {}
                    for hx in h.node.core.hg.consensus_events():
                        slot = dag.slot_of.get(hx)
                        if slot is None:
                            continue
                        ev = dag.events[slot]
                        for tx in ev.transactions:
                            keys[tx.hex()] = (
                                ev.round_received,
                                ev.consensus_timestamp,
                            )
                    result.committed_keys[h.idx] = keys
                snap = h.node.core.hg.stats_snapshot()
                result.fork_detected[h.idx] = (
                    snap.get("forked_creators", 0) > 0
                )
                result.ff_proof_rejects[h.idx] = int(
                    h.node._m_ff_rejects.value
                )
                # a completed fast-forward swapped the engine object the
                # node restarted with — attempt counters alone can't
                # distinguish a failed catch-up from a successful one
                swapped = (h.restarted
                           and h.node.core.hg is not h.engine_at_restart)
                result.fast_forwards[h.idx] = 1 if swapped else 0
                # membership plane: the epoch ledger every honest node
                # must agree on (epoch_agreement invariant)
                result.epochs[h.idx] = int(
                    getattr(h.node.core.hg, "epoch", 0)
                )
                result.membership_logs[h.idx] = [
                    (e["epoch"], e["kind"], e["pub"], e["boundary"])
                    for e in getattr(h.node.core.hg, "membership_log", ())
                ]
                # APPEND to any crash-time capture: a restarted node's
                # fresh recorder only holds post-restart records, and
                # the pre-crash narrative is the part a post-mortem
                # needs most
                result.flight_dumps[h.idx] = (
                    result.flight_dumps.get(h.idx, [])
                    + h.node.flight.dump()
                )
        finally:
            for h in handles:
                if h.alive:
                    await h.node.shutdown()
            if durable_root is not None:
                shutil.rmtree(durable_root, ignore_errors=True)

        result.fault_schedule = injector.schedule_fingerprint()
        counts: Dict[str, int] = {}
        for entry in injector.log:
            counts[entry["kind"]] = counts.get(entry["kind"], 0) + 1
        result.fault_counts = counts
        result.report = InvariantChecker().check(self.scenario, result)
        return result

    # ------------------------------------------------------------------

    async def _consensus_pass(self, handles, force: bool = False) -> None:
        """Run the consensus pipeline on every dirty live node, in node
        order (the runner-owned cadence that replaces the wall-clock
        _consensus_loop timer)."""
        for h in handles:
            if not h.alive:
                continue
            if not (force or h.node._consensus_dirty):
                continue
            h.node._consensus_dirty = False
            async with h.node.core_lock:
                await h.node._run_consensus_locked(0)

    async def _drain_commits(self, handles) -> None:
        """Wait until every committer has fully DELIVERED its queue —
        an empty queue still races the batch the committer already
        popped, and a wall-clock sleep there would make the sampled
        committed logs (and the reproducibility fingerprint) timing-
        dependent.  Queue.join() fires on the committer's task_done,
        after the last app ack."""
        for h in handles:
            if not h.alive:
                continue
            try:
                await asyncio.wait_for(h.node._commit_queue.join(), 60.0)
            except asyncio.TimeoutError:
                # a wedged committer (app refusing every retry) must not
                # hang the whole run — the invariant checker will say
                # what's missing
                pass

    async def _inject_fork(self, handles, byz: ByzantineSpec, rng, clock,
                           injector) -> dict:
        """The fork-emitting peer: mint an equivocating event (same
        creator, same index, different content) off the byzantine
        node's earliest live event and plant each branch at a different
        honest peer.  Fork-aware engines accept and later *detect* it;
        honest engines reject the branch at insert — which is exactly
        why the fork-attack-with-detection-disabled variant fails its
        fork_detected invariant."""
        from ..core.event import new_event

        h = handles[byz.node]
        if not h.alive:
            return {"injected": False, "reason": "byzantine node down"}
        core = h.node.core
        cid = byz.node
        async with h.node.core_lock:
            if core.byzantine:
                slots = core.hg.dag.cr_events[cid]
                base = core.hg.dag.events[slots[0]] if slots else None
            else:
                chain = core.hg.dag.chains[cid]
                base = (core.hg.dag.events[chain[chain.start]]
                        if len(chain) else None)
        if base is None:
            return {"injected": False, "reason": "no base event"}

        def _knows_fork_site(target_core) -> bool:
            # the target must hold the base AND a genuine event at the
            # forged index: without the genuine sibling, the branch is
            # just the next chain event (no equivocation to detect, and
            # honest engines would accept it as real)
            dag = target_core.hg.dag
            if base.hex() not in dag.slot_of:
                return False
            if target_core.byzantine:
                slots = dag.cr_events[cid]
            else:
                slots = list(dag.chains[cid])
            return any(
                dag.events[s].index == base.index + 1 for s in slots
            )

        ready = []
        for x in handles:
            if x.idx == byz.node or not x.alive:
                continue
            async with x.node.core_lock:
                if _knows_fork_site(x.node.core):
                    ready.append(x)
        if len(ready) < 2:
            return {"injected": False, "deferred": True}
        targets = rng.sample(ready, 2)
        accepted, rejected = [], []
        for t, tag in zip(targets, (b"a", b"b")):
            async with t.node.core_lock:
                other = t.node.core.head
            ev = new_event(
                [b"chaos-fork-" + tag], (base.hex(), other),
                h.key.pub_bytes, base.index + 1, timestamp=clock(),
            )
            ev.sign(h.key)
            try:
                async with t.node.core_lock:
                    t.node.core.insert_event(ev)
                accepted.append(t.idx)
            except ValueError as e:
                rejected.append({"node": t.idx, "error": str(e)})
        injector.record("fork_attack", byz.node, -1,
                        accepted=len(accepted))
        return {"injected": True, "accepted": accepted,
                "rejected": rejected}


def run_scenario(scenario: Scenario,
                 seed: Optional[int] = None,
                 kernel_class: str = "auto",
                 diet: bool = True) -> ScenarioResult:
    """One deterministic in-memory run; result carries the invariant
    report (``result.report.ok``).  ``diet=False`` pins the pre-diet
    kernels (fingerprint-parity differentials, ROADMAP item 4)."""
    return ScenarioRunner(scenario, seed=seed,
                          kernel_class=kernel_class, diet=diet).run()


# ----------------------------------------------------------------------
# live fleets


def _live_membership_op(runner, base_dir: str, op, log) -> bool:
    """Execute one scheduled churn verb against a live subprocess
    fleet: boot the joiner (spawn_joiner) and submit its subject-signed
    join tx — or submit a leave tx — through a live node's SubmitTx
    front door, exactly as an operator would.  The driver holds every
    scenario key (the datadirs it built), so leaves work even while the
    leaver is down.  Returns False when the submit should be retried
    (the via node is still booting/compiling)."""
    import os

    from ..crypto.keys import PemKeyFile
    from ..membership.transition import build_membership_tx
    from ..proxy.jsonrpc import JsonRpcClient, b64e
    from .. import testnet as tn

    if op.kind == "join":
        log(f"[chaos] boot joiner node {op.node}")
        runner.spawn_joiner(op.node)
    via = op.via if op.via is not None else 0
    d = os.path.join(base_dir, f"node{op.node}")
    key = PemKeyFile(d).read()
    addr = runner.ports.of(op.node)["gossip"]
    # stamp the CURRENT epoch (pipelined transitions accept stamps from
    # the current epoch through the projected apply epoch, so a burst
    # of same-epoch submissions queues cleanly)
    epoch = 0
    try:
        h = tn.fetch_healthz(runner.ports.of(via)["service"])
        epoch = int(h.get("epoch", 0))
    except Exception:
        pass
    tx = build_membership_tx(op.kind, key, addr, epoch)

    async def _submit() -> None:
        client = JsonRpcClient(runner.ports.of(via)["submit"],
                               timeout=15.0)
        try:
            await client.call("Babble.SubmitTx", b64e(tx))
        finally:
            await client.close()

    try:
        asyncio.run(_submit())
    except Exception as e:
        log(f"[chaos] {op.kind} tx for node {op.node} via {via} "
            f"failed ({e}); will retry")
        return False
    log(f"[chaos] submitted {op.kind} tx for node {op.node} "
        f"via {via} (epoch {epoch})")
    return True


def run_live(
    scenario: Scenario,
    base_dir: str,
    rate: float = 25.0,
    log=print,
) -> dict:
    """Execute a scenario against a live subprocess fleet.  Every node
    self-injects link faults from the shared (plan, seed) via
    ``--chaos_plan`` (see cli.py); this driver owns only the
    crash/restart schedule and the workload.  Returns a fleet report
    (stats sweep + per-node injected-fault counters); invariant depth
    belongs to the deterministic runner."""
    import os
    import threading
    import time

    from .. import testnet as tn

    os.makedirs(base_dir, exist_ok=True)
    plan_path = os.path.join(base_dir, "scenario.json")
    with open(plan_path, "w") as f:
        json.dump(scenario.to_dict(), f, indent=1)
    # exact link identities for every node's injector: gossip address
    # -> scenario index over founders AND scheduled joiners, so
    # founder->joiner links carry their planned faults and multiple
    # joiners never collide on one index (cli --chaos_addrs)
    ports = tn.PortLayout()
    addrs_path = os.path.join(base_dir, "chaos_addrs.json")
    with open(addrs_path, "w") as f:
        json.dump({
            ports.of(i)["gossip"]: i
            for i in range(scenario.nodes + scenario.joiners)
        }, f, indent=1)

    # one shared tick-0 for the whole fleet, restarts included — each
    # node's injector maps wall time to plan ticks from this epoch, so
    # a relaunched node rejoins the schedule in phase
    epoch = time.time()
    runner = tn.TestnetRunner(
        base_dir, scenario.nodes, heartbeat_ms=20, ports=ports,
        # membership plane: datadirs for scheduled joiners are prepared
        # up front; spawn_joiner boots each at its join op's tick
        joiners=scenario.joiners,
        # generous sync timeout: injected delays ride on top of real
        # RTTs, and byzantine-mode consensus per sync is heavy on
        # oversubscribed hosts — 200 ms would read every slow response
        # as a failure and drown the chaos signal in organic timeouts
        tcp_timeout_ms=1500,
        extra_node_args=[
            "--chaos_plan", plan_path, "--chaos_seed", str(scenario.seed),
            "--chaos_epoch", repr(epoch),
            "--chaos_addrs", addrs_path,
        ],
        # crash/restart runs HONEST since the durability plane landed:
        # a killed node replays its per-event WAL on top of the newest
        # checkpoint and resumes at its true head seq, so no peer ever
        # reads the restart as an equivocation (the old workaround —
        # fork-aware engines + tight checkpoints tolerating re-minted
        # indexes — is gone; see ROADMAP crash-recovery amnesia, fixed)
        byzantine=(scenario.engine == "byzantine"),
        checkpoints=bool(scenario.plan.crashes),
        wal=bool(scenario.plan.crashes or scenario.plan.disk),
    )
    duration = scenario.steps * scenario.tick_seconds
    sched = crash_schedule(scenario.plan)
    # driver-side injector: only its disk stream is consumed, so the
    # node processes' own (plan, seed) fault streams are untouched
    disk_injector = FaultInjector(scenario.plan, scenario.seed)
    report: dict = {"name": scenario.name, "seed": scenario.seed,
                    "duration_s": duration}
    runner.start()
    try:
        bomber = threading.Thread(
            target=lambda: asyncio.run(tn.bombard(
                scenario.nodes, rate, duration, runner.ports,
                seed=scenario.seed,
            )),
            daemon=True,
        )
        bomber.start()
        #: membership churn schedule (live mode): tick -> ops.  A
        #: failed submit (target node still compiling its first flush)
        #: re-queues the op a couple of seconds later instead of
        #: silently dropping the transition; a submit whose epoch stamp
        #: proves STALE (the fleet applied an earlier transition after
        #: the stamp was fetched — deterministic reject) is detected by
        #: the verify pass below and resubmitted with a fresh stamp,
        #: exactly as an operator's tooling would.
        member_sched: Dict[int, list] = {}
        for op in list(scenario.plan.joins) + list(scenario.plan.leaves):
            member_sched.setdefault(op.tick, []).append(op)
        #: ordered (op, verify_tick) list of submitted transitions:
        #: when op k's verify comes due, the fleet must have k+1
        #: transitions applied or in flight (pending + queue)
        awaiting: list = []
        ops_confirmed = 0

        def _epoch_of(node: int) -> int:
            h = tn.fetch_healthz(runner.ports.of(node)["service"])
            return int(h.get("epoch", 0))

        def _in_flight(node: int) -> int:
            """Transitions applied or in flight at ``node``: its epoch
            plus the pending boundary plus the queued tail — the one
            definition both the verify pass and the settle loop use."""
            h = tn.fetch_healthz(runner.ports.of(node)["service"])
            return (int(h.get("epoch", 0))
                    + (1 if h.get("epoch_pending") else 0)
                    + int(h.get("epoch_queue", 0)))

        # the driver walks the SAME epoch the nodes' injectors use, so
        # crash/restart actions stay in phase with the plan's partition
        # windows; ticks that elapsed during fleet boot are processed
        # immediately (their sleep clamps to zero)
        for tick in range(scenario.steps):
            for op in member_sched.pop(tick, []):
                ok = _live_membership_op(runner, base_dir, op, log)
                if ok:
                    awaiting.append((op, tick + 50))
                elif tick + 20 < scenario.steps:
                    member_sched.setdefault(tick + 20, []).append(op)
            while awaiting and awaiting[0][1] <= tick:
                op, _ = awaiting.pop(0)
                via = op.via if op.via is not None else 0
                try:
                    flight = _in_flight(via)
                except Exception:
                    flight = 0
                if flight >= ops_confirmed + 1:
                    ops_confirmed += 1
                elif tick + 20 < scenario.steps:
                    log(f"[chaos] {op.kind} for node {op.node} did not "
                        "take (stale stamp?); resubmitting")
                    member_sched.setdefault(tick + 1, []).append(op)
            for action, node_idx in sched.get(tick, ()):
                if action == "crash":
                    log(f"[chaos] tick {tick}: crash node {node_idx}")
                    runner.kill_node(node_idx)
                else:
                    if scenario.plan.disk is not None:
                        d = os.path.join(base_dir, f"node{node_idx}")
                        fired = apply_disk_faults(
                            disk_injector, scenario.plan.disk, node_idx,
                            os.path.join(d, "ckpt"),
                            os.path.join(d, "wal"),
                        )
                        if fired:
                            log(f"[chaos] tick {tick}: disk rot on node "
                                f"{node_idx}: {', '.join(fired)}")
                    log(f"[chaos] tick {tick}: restart node {node_idx}")
                    runner.restart_node(node_idx)
            deadline = epoch + (tick + 1) * scenario.tick_seconds
            time.sleep(max(0.0, deadline - time.time()))
        bomber.join(timeout=30)
        total = scenario.nodes + scenario.joiners
        n_ops = len(scenario.plan.joins) + len(scenario.plan.leaves)
        if n_ops:
            # membership settle (the live analog of the deterministic
            # runner's settle rounds): transitions submitted late in
            # the run still need their epoch boundary DECIDED, and an
            # oversubscribed CPU fleet decides rounds slowly while the
            # bombard load runs — poll (and re-drive any op the verify
            # loop left unconfirmed) until every reachable node applied
            # every scheduled transition, or the settle budget runs out
            all_ops = (list(scenario.plan.joins)
                       + list(scenario.plan.leaves))
            deadline = time.time() + 90.0
            next_redrive = 0.0
            while time.time() < deadline:
                views, flights = [], []
                for i in range(total):
                    try:
                        views.append(_epoch_of(i))
                        flights.append(_in_flight(i))
                    except Exception:
                        pass
                if views and all(v >= n_ops for v in views):
                    break
                if (flights and max(flights) < n_ops
                        and time.time() >= next_redrive):
                    # some transition neither applied nor in flight
                    # anywhere (a stale stamp was deterministically
                    # rejected): re-drive every op — duplicates of
                    # applied ones are rejected identically everywhere,
                    # so re-driving is idempotent
                    for op in all_ops:
                        _live_membership_op(runner, base_dir, op, log)
                    next_redrive = time.time() + 15.0
                time.sleep(2.0)
        report["stats"] = tn.watch_once(total, runner.ports)
        if n_ops:
            # membership plane: the fleet-wide epoch view — live churn's
            # pass/fail surface (every reachable node must have applied
            # every scheduled transition)
            epochs: Dict[str, object] = {}
            for i in range(total):
                try:
                    h = tn.fetch_healthz(runner.ports.of(i)["service"])
                    epochs[str(i)] = int(h.get("epoch", 0))
                except Exception as e:
                    epochs[str(i)] = f"error: {e}"
            report["epochs"] = epochs
        faults: Dict[str, Dict[str, float]] = {}
        for i in range(scenario.nodes):
            addr = runner.ports.of(i)["service"]
            try:
                text = tn.fetch_metrics(addr)
            except Exception as e:   # a crashed-for-good node has none
                faults[str(i)] = {"error": str(e)}
                continue
            per = {}
            for line in text.splitlines():
                if line.startswith("babble_chaos_faults_total{"):
                    kind = line.split('kind="', 1)[1].split('"', 1)[0]
                    per[kind] = float(line.rsplit(" ", 1)[1])
            faults[str(i)] = per
        report["chaos_faults"] = faults

        def _events(row) -> int:
            try:
                return int(row.get("consensus_events", "0"))
            except (TypeError, ValueError):
                return 0

        # every REACHABLE node must have advanced, and at least one node
        # must actually be reachable — without the any(), a fleet that
        # never booted (all rows are error rows) would vacuously pass
        report["advanced"] = all(
            "error" in row or _events(row) > 0 for row in report["stats"]
        ) and any(_events(row) > 0 for row in report["stats"])
    finally:
        runner.stop()
    return report

"""ForkHashgraph: byzantine-mode consensus engine (batch execution).

Pairs the host ForkDag (branch assignment, chain views) with the dense
branch kernels (ops/forks.py) and emits the same commit surface as
TpuHashgraph.  Differentially tested against consensus/byzantine.py
(the definition-first oracle) on forked DAGs, and against the honest
engine on fork-free DAGs.

Execution model is whole-DAG batch: each run_consensus() call re-runs the
pipeline over everything inserted so far from a fresh device state.  That
matches the byzantine bench shape (BASELINE "1024-node, 1/3 forks").

Live scope: the engine now exposes the full Core surface (known/diff/
full-event wire form/commit counters), so a node can run byzantine mode
end to end (Config.byzantine); the per-consensus cost is whole-window
batch, amortized by the node's consensus cadence, and memory is bounded
only by the run's history — the honest engine's rolling-window eviction
does not yet apply here (see README "Byzantine mode" scope note).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core.event import Event, FullWireEvent
from ..ops.forks import (
    FAME_TRUE,
    FAME_UNDEFINED,
    ForkConfig,
    ForkDag,
    fork_pipeline,
)
from ..ops.state import bucket as _bucket
from .ordering import consensus_sort


class ForkHashgraph:
    def __init__(
        self,
        participants: Dict[str, int],
        k: int = 2,
        commit_callback=None,
        verify_signatures: bool = False,
    ):
        self.participants = participants
        self.k = k
        self.dag = ForkDag(participants, k=k)
        self.commit_callback = commit_callback
        self.verify_signatures = verify_signatures
        self.consensus: List[str] = []
        self.consensus_transactions = 0
        self.last_committed_round_events = 0
        self._received: set = set()
        self._out = None
        self._dirty = True
        self._lcr_cache = -1    # host mirror: /Stats must never touch device

    @property
    def n(self) -> int:
        return len(self.participants)

    def insert_event(self, event: Event) -> None:
        if self.verify_signatures:
            if event.creator not in self.participants:
                raise ValueError("creator is not a participant")
            if not event.verify():
                raise ValueError("bad event signature")
        self.dag.insert(event)
        self._dirty = True

    # ------------------------------------------------------------------
    # Core surface (gossip protocol; mirrors TpuHashgraph's)

    def known(self) -> Dict[int, int]:
        """Per-CREATOR event counts.  Under equivocation this vector
        clock is approximate: two nodes can hold equally-sized but
        DIFFERENT event sets for a byzantine creator, and count-skip
        diffs alone then wedge at a stable fixpoint that never exchanges
        the symmetric difference (ADVICE r3 medium).  participant_events
        self-heals in two layers:

        1. tip exchange — when the peer's count is >= ours (suffix
           empty), our chain tip for that creator is sent anyway.  Equal
           sets drop it as a duplicate; diverged sets make the receiver
           insert a foreign tip whose self-parent is not its local tip,
           which IS the fork detection (ForkDag.insert allocates a
           branch), collapsing the undetectable case to the detected one.
        2. detected-fork resend — for creators with a locally detected
           fork, diffs ignore count-skip past the earliest divergence
           and resend the whole ambiguous suffix; receivers drop
           duplicates by hash and random gossip converges the fleet."""
        return {
            cid: len(self.dag.cr_events[cid])
            for cid in self.participants.values()
        }

    def _fork_suffix_start(self, cid: int) -> Optional[int]:
        """Earliest divergence index of creator cid, or None if no fork
        observed locally.  Events with seq < that index form the shared
        linear prefix: topological insertion puts exactly those events in
        the first ``div`` positions of cr_events (any seq>=div event on
        either branch self-parent-chains through the whole prefix), so
        count-skip is sound only there."""
        dag = self.dag
        alts = [
            dag.br_div[c]
            for c in range(cid * self.k, (cid + 1) * self.k)
            if dag.br_used[c] and dag.br_parent[c] >= 0
        ]
        return min(alts) if alts else None

    def participant_events(self, pub: str, skip: int) -> List[str]:
        cid = self.participants[pub]
        div = self._fork_suffix_start(cid)
        if div is not None:
            skip = min(skip, div)
        slots = self.dag.cr_events[cid]
        if slots and skip >= len(slots):
            # equal-or-ahead count: send the tip anyway (see known()
            # docstring, layer 1) so set divergence becomes detectable
            return [self.dag.events[slots[-1]].hex()]
        return [self.dag.events[s].hex() for s in slots[skip:]]

    def to_wire(self, event: Event) -> FullWireEvent:
        # the compact (creatorID, index) form is ambiguous under forks
        return FullWireEvent.from_event(event)

    def read_wire_info(self, w: FullWireEvent) -> Event:
        return w.to_event()

    # ------------------------------------------------------------------
    # consensus pipeline surface (Core.run_consensus calls these)

    def divide_rounds(self) -> None:
        pass          # lazy: _run() computes everything at find_order

    def decide_fame(self) -> None:
        pass

    def find_order(self) -> List[Event]:
        return self.run_consensus()

    @property
    def undetermined_count(self) -> int:
        return len(self.dag.events) - len(self._received)

    @property
    def last_consensus_round(self) -> Optional[int]:
        """Host mirror only (ADVICE r3): forcing ``self.lcr`` here would
        trigger a whole-DAG device pipeline recompute from the stats path
        and could race a concurrent consensus run.  The cache is advanced
        by every _run(); use ``self.lcr`` to force a computation."""
        lcr = self._lcr_cache
        return None if lcr < 0 else lcr

    def consensus_events_count(self) -> int:
        return len(self.consensus)

    def stats_snapshot(self) -> Dict[str, int]:
        return {
            "last_consensus_round": self._lcr_cache,
            "undetermined_events": self.undetermined_count,
            "consensus_events": len(self.consensus),
            "consensus_transactions": self.consensus_transactions,
            "last_committed_round_events": self.last_committed_round_events,
            "evicted_events": 0,      # no rolling window in batch mode
            "live_window": len(self.dag.events),
        }

    # ------------------------------------------------------------------

    def _run(self):
        if not self._dirty and self._out is not None:
            return self._out
        ne = len(self.dag.events)
        max_chain = max(
            (len(self.dag._chain_slots(c))
             for c in range(self.dag.b) if self.dag.br_used[c]),
            default=0,
        )
        max_lvl = max(self.dag.levels, default=0)
        cfg = ForkConfig(
            n=self.n, k=self.k,
            e_cap=_bucket(ne),
            s_cap=_bucket(max_chain + 1, 8),
            r_cap=_bucket(max_lvl + 2, 8),
        )
        batch = self.dag.build_batch(cfg)
        self._out = (cfg, fork_pipeline(cfg, batch))
        self._dirty = False
        self._lcr_cache = int(np.asarray(self._out[1].lcr))
        return self._out

    # ------------------------------------------------------------------
    # predicate surface (differential tests)

    def _slot(self, x: str) -> int:
        return self.dag.slot_of[x]

    def round(self, x: str) -> int:
        cfg, out = self._run()
        return int(np.asarray(out.round)[self._slot(x)])

    def witness(self, x: str) -> bool:
        cfg, out = self._run()
        return bool(np.asarray(out.witness)[self._slot(x)])

    def see(self, x: str, y: str) -> bool:
        cfg, out = self._run()
        sx, sy = self._slot(x), self._slot(y)
        la = np.asarray(out.la)
        det = np.asarray(out.det)
        br = self.dag.ebr[sy]
        cy = self.participants[self.dag.events[sy].creator]
        return bool(
            la[sx, br] >= self.dag.events[sy].index and not det[sx, cy]
        )

    def detects_fork(self, x: str, cid: int) -> bool:
        cfg, out = self._run()
        return bool(np.asarray(out.det)[self._slot(x), cid])

    def famous_of(self, r: int, x: str) -> Optional[bool]:
        cfg, out = self._run()
        if r < 0 or r >= cfg.r_cap:
            return None
        wslot = np.asarray(out.wslot)
        famous = np.asarray(out.famous)
        sx = self._slot(x)
        for col in range(cfg.b):
            if wslot[r, col] == sx:
                f = famous[r, col]
                return None if f == FAME_UNDEFINED else bool(f == FAME_TRUE)
        return None

    def max_round(self) -> int:
        cfg, out = self._run()
        return int(np.asarray(out.max_round))

    @property
    def lcr(self) -> int:
        cfg, out = self._run()
        return int(np.asarray(out.lcr))

    # ------------------------------------------------------------------

    def run_consensus(self) -> List[Event]:
        cfg, out = self._run()
        rr = np.asarray(out.rr)
        cts = np.asarray(out.cts)
        wslot = np.asarray(out.wslot)
        famous = np.asarray(out.famous)
        ne = len(self.dag.events)

        new_events: List[Event] = []
        for s in range(ne):
            if rr[s] < 0 or s in self._received:
                continue
            ev = self.dag.events[s]
            ev.round_received = int(rr[s])
            ev.consensus_timestamp = int(cts[s])
            new_events.append(ev)
            self._received.add(s)
        if not new_events:
            return []

        def prn(r: int) -> int:
            if r < 0 or r >= cfg.r_cap:
                return 0
            res = 0
            for col in range(cfg.b):
                if wslot[r, col] >= 0 and famous[r, col] == FAME_TRUE:
                    res ^= int(self.dag.events[int(wslot[r, col])].hex(), 16)
            return res

        new_events = consensus_sort(new_events, prn)
        for ev in new_events:
            self.consensus.append(ev.hex())
            self.consensus_transactions += len(ev.transactions)
        lcr = int(np.asarray(out.lcr))
        if lcr >= 1:
            rnd = np.asarray(out.round)[:ne]
            self.last_committed_round_events = int(
                np.count_nonzero(rnd == lcr - 1)
            )
        if self.commit_callback is not None:
            self.commit_callback(new_events)
        return new_events

    def consensus_events(self) -> List[str]:
        return list(self.consensus)

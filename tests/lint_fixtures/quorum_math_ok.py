"""Fixture: stale-quorum-math negative space — thresholds routed
through the epoch-aware helpers, plus innocent divisions by 3 that a
sloppier matcher would misfire on (capacity heuristics, averages)."""

from babble_tpu.membership.quorum import (
    attestation_quorum,
    supermajority,
    sync_quorum,
)


class EpochAwareNode:
    def __init__(self, participants, retired):
        self.participants = participants
        self.retired = retired

    def active_n(self):
        return len(self.participants) - len(self.retired)

    def super_majority(self):
        return supermajority(self.active_n())

    def probe_quorum(self):
        return sync_quorum(self.active_n())

    def proof_quorum(self):
        return attestation_quorum(self.active_n())


def window_heuristic(lvl_new):
    # a capacity estimate that merely divides by 3 is NOT quorum math
    return min(lvl_new, max(8, lvl_new // 3))


def padded(levels_max):
    # ... nor is // 3 + k for k != 1
    return (levels_max // 3 + 4 - 1).bit_length()

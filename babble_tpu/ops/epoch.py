"""Epoch transitions on the dense device state (membership plane).

``epoch_transition_arrays`` is the host-side half of
``TpuHashgraph.apply_epoch_transition``: re-shape the [*, N, N] device
state for a join (one appended participant column) or a leave (column
retired in the config, arithmetic tightened), and RESET every
consensus decision above the boundary round B so the new epoch
re-decides it under the new peer set.

Soundness sketch (why a reset + rescan is deterministic fleet-wide):

- every event with round_received <= B is already committed when the
  transition applies (apply requires ``lcr >= B``, and reception in a
  round requires being an ancestor of its famous witnesses, so a node
  that decided round B necessarily HOLDS everything received there);
- decisions for rounds > B made before the apply were never committed
  (the engine's commit gate holds them) and are discarded here;
- round assignment is a per-event function of ancestry plus the
  per-round threshold array ``sm`` — old rounds keep the old epoch's
  threshold, rounds above B get the new one — so a replica that first
  sees an event after its own apply assigns the same round a replica
  that held it before the apply recomputes in the rescan.

Epoch transitions are rare (seconds of fleet time per churn event at
worst), so this runs as plain numpy on host: correctness and
auditability over device residency.  The config change re-keys every
compiled program anyway — the AOT manifest records the new epoch's
shapes exactly like any other config (ops/aot.py).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from .state import DagConfig, DagState, FAME_UNDEFINED, repack_round_bits_np

I32 = np.int32


def widen_arrays(old: DagConfig, new: DagConfig,
                 a: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Grow the participant axis from old.n to new.n columns (a join):
    every N-shaped tensor gains sentinel-filled columns/rows for the
    new member, and the ``creator`` sentinel value moves from old.n to
    new.n.  Values are preserved column-for-column — survivor ids are
    stable across a join by construction (the new member always takes
    the next free id)."""
    no, nn = old.n, new.n
    if nn <= no:
        raise ValueError(f"widen requires new n {nn} > old n {no}")
    d = nn - no
    out = dict(a)
    fd_inf = np.asarray(new.fd_inf)

    def pad_cols(x, fill):
        pad = np.full(x.shape[:-1] + (d,), fill, x.dtype)
        return np.concatenate([x, pad], axis=-1)

    # the creator sentinel (padding lanes + row e_cap) was old.n; that
    # value now names the new member — remap it to the new sentinel
    out["creator"] = np.where(a["creator"] == no, nn,
                              a["creator"]).astype(I32)
    out["la"] = pad_cols(a["la"], -1)
    out["fd"] = pad_cols(a["fd"], fd_inf)
    out["wslot"] = pad_cols(a["wslot"], -1)
    out["famous"] = pad_cols(a["famous"], np.int8(FAME_UNDEFINED))
    # ce/cnt/s_off carry an (n+1)-th sentinel row: the old sentinel row
    # becomes the new member's (it is init-valued by construction —
    # every ingest restores it) and fresh sentinel rows are appended
    ce_pad = np.full((d,) + a["ce"].shape[1:], -1, a["ce"].dtype)
    out["ce"] = np.concatenate([a["ce"], ce_pad], axis=0)
    out["cnt"] = np.concatenate([a["cnt"], np.zeros(d, a["cnt"].dtype)])
    out["s_off"] = np.concatenate(
        [a["s_off"], np.zeros(d, a["s_off"].dtype)]
    )
    return out


def epoch_transition_arrays(
    old: DagConfig, new: DagConfig, state: DagState, boundary: int
) -> Dict[str, np.ndarray]:
    """Numpy image of the post-transition DagState, before the round
    rescan: widened/retired shapes, decisions above ``boundary`` reset,
    per-round thresholds split at the boundary.  The caller re-uploads
    and then reruns round assignment for every event whose stored round
    exceeds the boundary (engine._rescan_rounds_above)."""
    a = {name: np.asarray(getattr(state, name))
         for name in DagState._fields}
    if new.n != old.n:
        a = widen_arrays(old, new, a)

    r_off = int(a["r_off"])
    r_cap = new.r_cap
    b_loc = boundary - r_off
    if not (0 <= b_loc < r_cap):
        raise ValueError(
            f"epoch boundary {boundary} outside the round window "
            f"(r_off {r_off}, r_cap {r_cap})"
        )

    # rounds above the boundary: fame undecided, witness tables empty
    # (the rescan re-registers under the new config), reception reset
    a["famous"] = a["famous"].copy()
    a["famous"][b_loc + 1:] = FAME_UNDEFINED
    a["wslot"] = a["wslot"].copy()
    a["wslot"][b_loc + 1:] = -1
    held = a["rr"] > boundary
    a["rr"] = np.where(held, -1, a["rr"]).astype(I32)
    a["cts"] = np.where(held, 0, a["cts"])
    a["lcr"] = np.asarray(min(int(a["lcr"]), boundary), I32)

    # per-round thresholds: old rounds keep the old epoch's quorum,
    # the boundary's future (and the compact backfill sentinel row)
    # switch to the new epoch's
    sm = a["sm"].copy()
    sm[b_loc + 1:] = new.super_majority
    a["sm"] = sm.astype(I32)

    # rounds above the boundary are rescanned; reset them here so
    # max_round is consistent even when the rescan set is empty
    stale_round = a["round"] > boundary
    a["round"] = np.where(stale_round, -1, a["round"]).astype(I32)
    a["witness"] = a["witness"] & ~stale_round
    live = (np.arange(len(a["seq"])) < int(a["n_events"])) \
        & (a["seq"] >= 0)
    mr = a["round"][live].max() if live.any() else -1
    a["max_round"] = np.asarray(int(mr), I32)

    # packed witness bitplanes (kernel diet): recompute from the
    # re-shaped wide tensors — a join widens the participant axis, so
    # the uint8 LANE count re-buckets (ceil(n/8)) with it, and the
    # boundary resets above already cleared the famous/wslot rows the
    # planes derive from
    a["mbr"], a["fmr"] = repack_round_bits_np(
        new, a["wslot"], a["famous"], a["mbit"]
    )
    return a

"""Snapshot trust discipline: peer-supplied state must be proof-checked.

Verified fast-forward (ISSUE 8, store/proof.py) closes the
protocol-aware-recovery hole — a byzantine bootstrap peer feeding a
forged state — but only if EVERY path that builds an engine from
peer-supplied snapshot bytes actually reaches the proof-verification
helpers before (or around) adopting it.  One new catch-up path that
calls ``load_snapshot`` and skips verification quietly reopens the
hole.

Detection rides the PR-4 project call graph, the same shape as
``wal-before-gossip``: a function whose calls include ``load_snapshot``
(the only constructor for peer-supplied snapshot *bytes*; the local
disk path is ``load_checkpoint``/``load_checkpoint_tolerant`` and is
out of scope) must reach one of the proof helpers —
``verify_snapshot_digest`` / ``verify_snapshot_proof`` /
``verify_attestation`` — either directly or through its same-object
call closure.  ``store/checkpoint.py`` itself (the definition site) is
exempt, as are the proof/test helpers.

Presence, not ordering or conditionality, is what is checked
statically; the runtime gate (``Config.ff_verify``) and the
reject-before-adopt ordering live in ``Node._fast_forward``.
"""

from __future__ import annotations

import re
from typing import Iterator, List

from .engine import FileContext, Finding, Rule
from .graph import CallSite, FunctionInfo, ProjectContext

_VERIFY_RE = re.compile(
    r"(^|\.)(_?verify_snapshot_digest|_?verify_snapshot_proof|"
    r"_?verify_attestation|_?verify_ff_\w+)$"
)

#: modules where load_snapshot legitimately appears unverified: its own
#: definition module, and the proof module documenting it
_EXEMPT_PATH_RE = re.compile(r"store[/\\](checkpoint|proof)\.py$")


def _is_load_snapshot(site: CallSite) -> bool:
    if site.text == "load_snapshot" or site.text.endswith(".load_snapshot"):
        return True
    return any(q.endswith(":load_snapshot") for q in site.callees)


def _is_verify(site: CallSite) -> bool:
    return bool(_VERIFY_RE.search(site.text))


def _self_closure(project: ProjectContext,
                  fi: FunctionInfo) -> List[FunctionInfo]:
    """``fi`` plus every method it transitively calls on ``self``
    (all edges — proof reachability is about the dynamic extent)."""
    out: List[FunctionInfo] = []
    seen = set()
    queue = [fi.qualname]
    while queue:
        q = queue.pop()
        if q in seen:
            continue
        seen.add(q)
        f = project.functions.get(q)
        if f is None:
            continue
        out.append(f)
        if f.cls is None:
            continue
        for site in f.calls:
            if site.via_self:
                nxt = project.lookup_method(
                    (f.module, f.cls), site.text.split(".")[1]
                )
                if nxt is not None:
                    queue.append(nxt)
    return out


class UnverifiedSnapshotAdoptRule(Rule):
    name = "unverified-snapshot-adopt"
    description = (
        "a path that builds an engine from peer-supplied snapshot bytes "
        "(load_snapshot) must reach the signed-state-proof verification "
        "helpers in its call closure — an unverified adoption reopens "
        "the forged-bootstrap hole (FAST'18 protocol-aware recovery)"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        project = ctx.project
        if project is None:
            return
        if _EXEMPT_PATH_RE.search(ctx.path):
            return
        for fi in project.functions.values():
            if fi.path != ctx.path:
                continue
            load_sites = [s for s in fi.calls if _is_load_snapshot(s)]
            if not load_sites:
                continue
            closure = (
                _self_closure(project, fi) if fi.cls is not None else [fi]
            )
            sites = [s for f in closure for s in f.calls]
            if any(_is_verify(s) for s in sites):
                continue
            yield self.finding(
                ctx, load_sites[0].node,
                f"`{fi.name}` builds an engine from peer-supplied "
                "snapshot bytes but its call closure never reaches a "
                "state-proof verification helper "
                "(verify_snapshot_digest / verify_snapshot_proof / "
                "verify_attestation) — an unverified adoption lets a "
                "byzantine bootstrap peer feed a forged state",
            )

"""Runtime configuration (reference node/config.go:26-57)."""

from __future__ import annotations

import logging
from dataclasses import dataclass, field


def _default_logger() -> logging.Logger:
    logger = logging.getLogger("babble_tpu")
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s %(message)s"
        ))
        logger.addHandler(h)
        logger.setLevel(logging.WARNING)
    return logger


@dataclass
class Config:
    heartbeat: float = 1.0          # seconds (reference default 1000ms)
    tcp_timeout: float = 1.0        # seconds
    cache_size: int = 500           # engine event capacity hint
    logger: logging.Logger = field(default_factory=_default_logger)

    @classmethod
    def test_config(cls, heartbeat: float = 0.005) -> "Config":
        logger = logging.getLogger("babble_tpu.test")
        logger.setLevel(logging.WARNING)
        return cls(heartbeat=heartbeat, tcp_timeout=0.2, logger=logger)

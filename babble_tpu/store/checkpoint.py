"""Checkpoint / resume of consensus state.

The reference has no persistence at all — its Store interface is the
"designed-but-unused persistence seam" (reference hashgraph/store.go:25-41,
README.md:140-141) and a crashed node can never rejoin.  Here the seam is
real: a checkpoint captures

- the host DAG (events in wire form, topologically ordered — the compact
  (creatorID, index) parent encoding of reference event.go:244-254),
- the consensus log + commit bookkeeping,
- the dense device tensors (DagState), so resume is a bulk load instead of
  a full re-ingest.

Layout: ``<dir>/meta.msgpack`` + ``<dir>/device.npz``.  Writes go to a
temp directory swapped in atomically, so a crash mid-save never corrupts
the previous checkpoint.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Callable, Dict, List, Optional

import msgpack
import numpy as np

from ..consensus.engine import TpuHashgraph
from ..ops.state import DagConfig, DagState

FORMAT_VERSION = 1

_META = "meta.msgpack"
_DEVICE = "device.npz"


def save_checkpoint(engine: TpuHashgraph, path: str) -> None:
    """Write a consistent snapshot of `engine` to directory `path`."""
    engine.flush()  # device state must reflect every inserted event

    dag = engine.dag
    wire_events = []
    for ev in dag.events:  # slot order == topological order
        w = dag.to_wire(ev)
        wire_events.append(w.pack())

    meta = {
        "version": FORMAT_VERSION,
        "participants": sorted(engine.participants.items()),
        "cfg": list(engine.cfg),
        "verify_signatures": dag.verify_signatures,
        "events": wire_events,
        "consensus": engine.consensus,
        "consensus_transactions": engine.consensus_transactions,
        "last_committed_round_events": engine.last_committed_round_events,
        "received": sorted(engine._received),
    }

    arrays = {
        name: np.asarray(getattr(engine.state, name))
        for name in DagState._fields
    }

    tmp = tempfile.mkdtemp(dir=os.path.dirname(os.path.abspath(path)) or ".")
    try:
        with open(os.path.join(tmp, _META), "wb") as f:
            f.write(msgpack.packb(meta, use_bin_type=True))
        np.savez_compressed(os.path.join(tmp, _DEVICE), **arrays)
        if os.path.isdir(path):
            old = path + ".old"
            os.rename(path, old)
            os.rename(tmp, path)
            shutil.rmtree(old)
        else:
            os.rename(tmp, path)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load_checkpoint(
    path: str,
    commit_callback: Optional[Callable] = None,
) -> TpuHashgraph:
    """Reconstruct an engine from a checkpoint directory."""
    with open(os.path.join(path, _META), "rb") as f:
        meta = msgpack.unpackb(f.read(), raw=False, strict_map_key=False)
    if meta["version"] != FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint version {meta['version']}")

    participants: Dict[str, int] = {k: int(v) for k, v in meta["participants"]}
    cfg = DagConfig(*meta["cfg"])
    engine = TpuHashgraph(
        participants,
        commit_callback=commit_callback,
        verify_signatures=meta["verify_signatures"],
        e_cap=cfg.e_cap, s_cap=cfg.s_cap, r_cap=cfg.r_cap,
    )
    engine.cfg = cfg

    # Replay the host index.  Signatures were verified before the events
    # entered the saved state — skip re-verification for bulk-load speed.
    from ..core.event import WireEvent

    dag = engine.dag
    saved_verify = dag.verify_signatures
    dag.verify_signatures = False
    try:
        for packed in meta["events"]:
            dag.insert(dag.read_wire_info(WireEvent.unpack(packed)))
    finally:
        dag.verify_signatures = saved_verify
    dag.pending.clear()  # the device tensors below already contain them

    import jax.numpy as jnp

    with np.load(os.path.join(path, _DEVICE)) as z:
        engine.state = DagState(
            **{name: jnp.asarray(z[name]) for name in DagState._fields}
        )

    engine.consensus = list(meta["consensus"])
    engine.consensus_transactions = meta["consensus_transactions"]
    engine.last_committed_round_events = meta["last_committed_round_events"]
    engine._received = set(meta["received"])
    return engine

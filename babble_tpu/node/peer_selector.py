"""Gossip partner selection (reference node/peer_selector.go:24-61)."""

from __future__ import annotations

import random
from typing import List, Optional

from ..net.peers import Peer, exclude_peer


class PeerSelector:
    def peers(self) -> List[Peer]:
        raise NotImplementedError

    def next(self) -> Optional[Peer]:
        raise NotImplementedError

    def update_last(self, peer_addr: str) -> None:
        raise NotImplementedError


class RandomPeerSelector(PeerSelector):
    """Uniform choice excluding self and the last-gossiped peer.

    The default RNG is seeded from the node's own address, NOT OS
    entropy (found by the consensus-nondeterminism taint pass): peer
    choice shapes the DAG, and an unseeded stream here was the last
    per-node decision the chaos plane could not replay from identity +
    seed alone.  Distinct nodes still draw distinct streams (different
    addresses), which is all the jitter was ever for; callers that
    genuinely want shared-seed control pass ``rng`` explicitly."""

    def __init__(self, peers: List[Peer], local_addr: str,
                 rng: Optional[random.Random] = None):
        _, self._peers = exclude_peer(peers, local_addr)
        self.local_addr = local_addr
        self.last: Optional[str] = None
        # string seeding is content-based (not hash()-randomized), so
        # the stream is stable across processes and PYTHONHASHSEED
        self._rng = rng if rng is not None else random.Random(
            f"peer-selector:{local_addr}")

    def peers(self) -> List[Peer]:
        return list(self._peers)

    def add_peer(self, peer: Peer) -> None:
        """Membership plane: admit a newly-joined validator as a
        gossip target (idempotent; self never added)."""
        if peer.net_addr == self.local_addr:
            return
        if any(p.net_addr == peer.net_addr for p in self._peers):
            return
        self._peers.append(peer)

    def remove_peer(self, addr: str) -> None:
        """Membership plane: stop gossiping to a departed validator."""
        _, self._peers = exclude_peer(self._peers, addr)
        if self.last == addr:
            self.last = None

    def next(self) -> Optional[Peer]:
        candidates = self._peers
        if len(candidates) > 1 and self.last is not None:
            _, candidates = exclude_peer(candidates, self.last)
        if not candidates:
            return None
        return self._rng.choice(candidates)

    def update_last(self, peer_addr: str) -> None:
        self.last = peer_addr

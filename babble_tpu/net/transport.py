"""Transport interface (reference net/transport.go:21-70).

A transport delivers inbound RPCs on an asyncio queue (``consumer``) and
performs outbound request/response syncs.  The RPC object carries a future
the handler resolves with its response — the async mirror of the
reference's ``RPCResponse`` channel.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Optional, Union

from .commands import SyncRequest, SyncResponse


@dataclass
class RPC:
    command: SyncRequest
    _future: "asyncio.Future[SyncResponse]" = field(
        default_factory=lambda: asyncio.get_event_loop().create_future()
    )

    def respond(self, resp: Optional[SyncResponse], error: Optional[str] = None):
        if self._future.done():
            return
        if error is not None:
            self._future.set_exception(TransportError(error))
        else:
            self._future.set_result(resp)

    async def response(self) -> SyncResponse:
        return await self._future


class TransportError(Exception):
    pass


class Transport:
    """Abstract transport. Implementations: InmemTransport, TCPTransport."""

    @property
    def consumer(self) -> "asyncio.Queue[RPC]":
        raise NotImplementedError

    def local_addr(self) -> str:
        raise NotImplementedError

    async def sync(
        self, target: str, req: SyncRequest, timeout: Optional[float] = None
    ) -> SyncResponse:
        """Send a sync request to target and await its response."""
        raise NotImplementedError

    async def request(self, target, req, timeout: Optional[float] = None):
        """Generic verb-tagged RPC; defaults to the sync plumbing (in-
        process transports pass request objects through unchanged)."""
        return await self.sync(target, req, timeout)

    async def close(self) -> None:
        raise NotImplementedError

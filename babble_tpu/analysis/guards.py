"""Lock/guard discipline: ``held-guard-escape``.

``asyncio.Lock`` is not reentrant.  The gossip loop serializes all core
access behind ``self.core_lock``; the discipline that keeps it
deadlock-free is purely conventional — helpers that run under the lock
(``_run_consensus_locked``) must never acquire it, and their docstrings
say so.  Nothing enforced it: move one ``async with self.core_lock``
into a helper that is also called from a locked context and the node
freezes forever on its own lock, with no traceback (the chaos tier
would find it as a liveness violation, minutes later, per seed).

This rule enforces the convention statically, project-wide: inside the
body of a ``with``/``async with`` on a lockish ``self.<attr>``
(``lock``/``mutex``/``sem`` word segments — the same naming heuristic
the race rule uses), any call to ``self.m(...)`` whose *transitive
guard closure* (graph.ProjectContext.guard_closure) re-acquires the
same attribute is a finding.  The closure walks ``self.m()`` edges
only: a method of a DIFFERENT object acquiring its own ``core_lock``
is that object's (distinct) lock, not a re-entry.

The rule checks sync and async functions alike — a sync helper cannot
await, but it can call a coroutine-returning factory or be refactored
async later; flagging the call-under-guard is cheap insurance either
way.  Re-entry through unresolved calls (callbacks, getattr dispatch)
is invisible; the rule's contract is "the resolvable part of the graph
is clean", not "no deadlock exists".
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from .engine import FileContext, Finding, Rule
from .graph import lockish_name


class HeldGuardEscapeRule(Rule):
    name = "held-guard-escape"
    description = (
        "a call made while holding a lockish self.<attr> guard reaches "
        "a method that re-acquires the same guard (directly or through "
        "its call chain) — asyncio locks are not reentrant; the task "
        "deadlocks on itself"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        project = getattr(ctx, "project", None)
        if project is None:
            return
        module = project.path_module.get(ctx.path)
        if module is None:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from self._check_function(
                        ctx, project, module, node.name, sub)

    def _check_function(self, ctx, project, module: str, cls: str,
                        fn) -> Iterator[Finding]:
        yield from self._walk(ctx, project, module, cls, fn.name,
                              fn.body, held=frozenset())

    def _walk(self, ctx, project, module: str, cls: str, fname: str,
              body: List[ast.stmt], held: frozenset) -> Iterator[Finding]:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # own schedule, own (future) guard context
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired: Set[str] = set()
                for item in stmt.items:
                    cx = item.context_expr
                    if (isinstance(cx, ast.Attribute)
                            and isinstance(cx.value, ast.Name)
                            and cx.value.id == "self"
                            and lockish_name(cx.attr)):
                        acquired.add(cx.attr)
                    yield from self._calls_in(
                        ctx, project, module, cls, fname, cx, held)
                yield from self._walk(ctx, project, module, cls, fname,
                                      stmt.body, held | acquired)
            elif isinstance(stmt, (ast.If, ast.While)):
                yield from self._calls_in(
                    ctx, project, module, cls, fname, stmt.test, held)
                yield from self._walk(ctx, project, module, cls, fname,
                                      stmt.body, held)
                yield from self._walk(ctx, project, module, cls, fname,
                                      stmt.orelse, held)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                yield from self._calls_in(
                    ctx, project, module, cls, fname, stmt.iter, held)
                yield from self._walk(ctx, project, module, cls, fname,
                                      stmt.body, held)
                yield from self._walk(ctx, project, module, cls, fname,
                                      stmt.orelse, held)
            elif isinstance(stmt, ast.Try):
                yield from self._walk(ctx, project, module, cls, fname,
                                      stmt.body, held)
                for h in stmt.handlers:
                    yield from self._walk(ctx, project, module, cls,
                                          fname, h.body, held)
                yield from self._walk(ctx, project, module, cls, fname,
                                      stmt.orelse, held)
                yield from self._walk(ctx, project, module, cls, fname,
                                      stmt.finalbody, held)
            else:
                yield from self._calls_in(
                    ctx, project, module, cls, fname, stmt, held)

    def _calls_in(self, ctx, project, module: str, cls: str, fname: str,
                  expr: ast.AST, held: frozenset) -> Iterator[Finding]:
        if not held:
            return
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                meth = node.func.attr
                qual = project.lookup_method((module, cls), meth)
                if qual is not None:
                    reacquired = held & project.guard_closure(qual)
                    for g in sorted(reacquired):
                        yield self.finding(
                            ctx, node,
                            f"`self.{meth}(...)` re-acquires "
                            f"`self.{g}` already held by `{fname}` — "
                            "asyncio locks are not reentrant; the task "
                            "deadlocks on itself (pass control in "
                            "already-locked form, like "
                            "`_run_consensus_locked`)",
                        )
            stack.extend(ast.iter_child_nodes(node))

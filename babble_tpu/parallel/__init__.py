"""Multi-chip parallelism: device meshes, sharding specs, sharded pipeline.

The reference scales by running N independent Go processes that gossip over
TCP (node/node.go, net/) — replicated-state-machine parallelism.  On TPU the
batch/simulation path instead shards ONE consensus computation across chips
(SURVEY.md §2.6): the event axis ("ev", the DAG's unbounded long-context
axis) and the participant axis ("p", the witness/vote axis) are laid out
over a 2D ``jax.sharding.Mesh``, shardings are annotated on the DagState
pytree, and XLA inserts the ICI collectives (all-gathers of witness rows,
psum-style vote reductions) that replace babble's vote-counting loops.
"""

from .mesh import make_mesh
from .multihost import (
    bootstrap, broadcast_batch, global_mesh, make_multihost_step,
)
from .sharded import (
    batch_shardings,
    consensus_step_impl,
    make_sharded_step,
    pad_cfg_for_mesh,
    place_state,
    sharded_init_state,
    state_shardings,
    state_specs,
)

__all__ = [
    "bootstrap", "broadcast_batch", "global_mesh", "make_multihost_step",
    "make_mesh",
    "state_specs",
    "state_shardings",
    "batch_shardings",
    "place_state",
    "consensus_step_impl",
    "make_sharded_step",
    "pad_cfg_for_mesh",
]

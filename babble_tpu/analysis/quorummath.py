"""stale-quorum-math: quorum thresholds must route through the
epoch-aware helpers (membership plane, ISSUE 9).

With dynamic membership, the participant count is EPOCH STATE: a quorum
expression inlined at a call site — ``2 * n // 3 (+ 1)`` or
``n // 3 + 1`` — silently closes over whichever ``n`` was in scope when
the line was written, and keeps enforcing the OLD epoch's threshold
after a join/leave re-shapes the fleet.  That bug class is invisible to
tests that never churn membership, which is every test written before
the churn chaos tier existed.  The fix shape is mechanical: call
``babble_tpu.membership.quorum.supermajority / sync_quorum /
attestation_quorum`` with the epoch's active count.

Detection is syntactic and deliberately precise — only the two
unambiguous quorum shapes are flagged, so capacity heuristics that
merely divide by 3 (``lvl_new // 3`` window sizing) stay clean:

- ``2 * X // 3`` (either operand order of the multiplication), with or
  without a trailing ``+ 1``;
- ``X // 3 + 1`` (the attestation-quorum shape).

The helper module itself is exempt (it is the definition site), as are
test fixtures.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from .engine import FileContext, Finding, Rule

#: the one module allowed to spell the arithmetic out
_EXEMPT_PATH_RE = re.compile(r"membership[/\\]quorum\.py$")


def _is_const(node: ast.AST, value: int) -> bool:
    return isinstance(node, ast.Constant) and node.value == value


def _is_two_thirds(node: ast.AST) -> bool:
    """``2 * X // 3`` or ``X * 2 // 3``."""
    if not (isinstance(node, ast.BinOp)
            and isinstance(node.op, ast.FloorDiv)
            and _is_const(node.right, 3)):
        return False
    left = node.left
    return (isinstance(left, ast.BinOp) and isinstance(left.op, ast.Mult)
            and (_is_const(left.left, 2) or _is_const(left.right, 2)))


def _is_third_plus_one(node: ast.AST) -> bool:
    """``X // 3 + 1`` (X itself not already the 2/3 shape — that form
    is flagged at the inner node with the supermajority message)."""
    if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add)
            and _is_const(node.right, 1)):
        return False
    left = node.left
    return (isinstance(left, ast.BinOp)
            and isinstance(left.op, ast.FloorDiv)
            and _is_const(left.right, 3)
            and not _is_two_thirds(left))


class StaleQuorumMathRule(Rule):
    name = "stale-quorum-math"
    description = (
        "quorum thresholds (2*n//3, n//3+1) must route through the "
        "epoch-aware helpers in babble_tpu.membership.quorum — an "
        "inlined expression keeps enforcing a stale epoch's threshold "
        "after membership churn"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if _EXEMPT_PATH_RE.search(ctx.path):
            return
        for node in ast.walk(ctx.tree):
            if _is_two_thirds(node):
                yield self.finding(
                    ctx, node,
                    "inlined 2/3 quorum expression; route through "
                    "membership.quorum.supermajority / sync_quorum "
                    "with the epoch's active participant count",
                )
            elif _is_third_plus_one(node):
                yield self.finding(
                    ctx, node,
                    "inlined n//3+1 quorum expression; route through "
                    "membership.quorum.attestation_quorum with the "
                    "epoch's active participant count",
                )

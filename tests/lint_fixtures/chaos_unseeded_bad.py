"""Fixture for chaos-unseeded-random: global-RNG draws in chaos code.

The filename carries the ``chaos`` segment that puts this file in the
rule's scope; the seeded idioms at the bottom must NOT be flagged.
"""

import random
from random import choice, random as rand


def decide_drop(p):
    return random.random() < p          # MARK: chaos-unseeded-random


def pick_peer(peers):
    random.shuffle(peers)               # MARK: chaos-unseeded-random
    return choice(peers)                # MARK: chaos-unseeded-random


def jitter_ms():
    return rand() * 10.0                # MARK: chaos-unseeded-random


def make_rng():
    return random.Random()              # MARK: chaos-unseeded-random


# ---- the correct, seeded idioms: not flagged ----


def seeded_decide_drop(rng: random.Random, p: float) -> bool:
    return rng.random() < p


def seeded_rng(seed: int, src: int, dst: int) -> random.Random:
    return random.Random(f"chaos:{seed}:{src}>{dst}")

"""Fork-aware (byzantine) consensus: differential tests.

Three-way anchor chain:
- ForkOracle (definition-first, hashgraph paper) vs the honest oracle on
  fork-free DAGs — proves the fork-aware semantics degrade to reference
  behavior when nobody equivocates;
- dense branch kernels (ops/forks.py via ForkHashgraph) vs ForkOracle on
  forked DAGs — the byzantine-mode correctness argument;
- fork bookkeeping unit checks (budget, detection, seeing).

The reference has no counterpart to any of this: it rejects forks at
insert (hashgraph.go:366-396) and skips fork detection in See
(hashgraph.go:149-154).
"""

import pytest

from babble_tpu.consensus.byzantine import ForkOracle
from babble_tpu.consensus.fork_engine import ForkHashgraph
from babble_tpu.consensus.oracle import OracleHashgraph
from babble_tpu.ops.forks import ForkBudgetError
from babble_tpu.sim import random_byzantine_dag, random_gossip_dag
from babble_tpu.store.inmem import InmemStore


def _fill(dag, *engines):
    for ev in dag.events:
        for e in engines:
            e.insert_event(ev.clone())


def _assert_match(dag, fo: ForkOracle, fh: ForkHashgraph):
    for ev in dag.events:
        x = ev.hex()
        assert fh.round(x) == fo.round(x), f"round {x[:10]}"
        assert fh.witness(x) == fo.witness(x), f"witness {x[:10]}"
    # fame parity on every witness of every round
    for r in range(fo.max_round() + 1):
        for w in fo.round_witnesses(r):
            assert fh.famous_of(r, w) == fo.famous[w], f"fame r={r} {w[:10]}"
    assert fh.consensus_events() == fo.consensus_events()
    assert fh.lcr == fo.lcr


# ----------------------------------------------------------------------


@pytest.mark.parametrize("n,e,seed", [(4, 150, 1), (5, 200, 2)])
def test_fork_oracle_degrades_to_reference_on_honest_dags(n, e, seed):
    dag = random_gossip_dag(n, e, seed=seed)
    fo = ForkOracle(dag.participants)
    store = InmemStore(dag.participants, cache_size=100_000)
    oh = OracleHashgraph(
        participants=dag.participants, store=store, verify_signatures=False
    )
    _fill(dag, fo, oh)
    fo.run_consensus()
    oh.divide_rounds()
    oh.decide_fame()
    oh.find_order()
    assert fo.consensus_events() == oh.consensus_events()
    for ev in dag.events:
        assert fo.round(ev.hex()) == oh.round(ev.hex())
        assert fo.witness(ev.hex()) == oh.witness(ev.hex())


@pytest.mark.parametrize("k", [1, 2])
def test_dense_matches_oracle_on_honest_dag(k):
    dag = random_gossip_dag(4, 120, seed=7)
    fo = ForkOracle(dag.participants)
    fh = ForkHashgraph(dag.participants, k=k)
    _fill(dag, fo, fh)
    fo.run_consensus()
    fh.run_consensus()
    _assert_match(dag, fo, fh)


@pytest.mark.parametrize(
    "n,e,rate,seed",
    [(6, 200, 0.08, 3), (7, 260, 0.05, 4), (9, 300, 0.1, 5)],
)
def test_dense_matches_oracle_on_byzantine_dag(n, e, rate, seed):
    dag = random_byzantine_dag(n, e, seed=seed, fork_rate=rate)
    fo = ForkOracle(dag.participants)
    fh = ForkHashgraph(dag.participants, k=2)
    _fill(dag, fo, fh)
    fo.run_consensus()
    fh.run_consensus()
    pairs = sum(len(v) for v in fo._fork_pairs.values())
    assert pairs > 0, "generator produced no forks"
    _assert_match(dag, fo, fh)


def test_forked_events_are_unseeable_once_detected():
    """A detector of creator c's fork sees none of c's events (paper
    semantics) — checked on both oracle and dense engine."""
    dag = random_byzantine_dag(6, 200, seed=3, fork_rate=0.08)
    fo = ForkOracle(dag.participants)
    fh = ForkHashgraph(dag.participants, k=2)
    _fill(dag, fo, fh)
    fo.run_consensus()
    fh.run_consensus()
    checked = 0
    for cid, pairs in fo._fork_pairs.items():
        if not pairs:
            continue
        for x in dag.events[-20:]:
            hx = x.hex()
            det_o = fo.detects_fork(hx, cid)
            assert fh.detects_fork(hx, cid) == det_o
            if not det_o:
                continue
            for y in dag.events:
                if fo.participants[y.creator] == cid:
                    assert not fo.see(hx, y.hex())
                    assert not fh.see(hx, y.hex())
                    checked += 1
    assert checked > 0, "no detection case exercised"


def test_fork_budget_rejects_spam():
    """Beyond K-1 forks, the branch budget cuts the equivocator off (the
    dense engine's DoS guard; a real deployment would blacklist)."""
    dag = random_byzantine_dag(
        6, 300, seed=11, fork_rate=0.5, forks_per_node=5
    )
    fh = ForkHashgraph(dag.participants, k=2)
    with pytest.raises(ForkBudgetError):
        for ev in dag.events:
            fh.insert_event(ev.clone())
    # a budget matching the stream accepts it fine
    fh6 = ForkHashgraph(dag.participants, k=6)
    for ev in dag.events:
        fh6.insert_event(ev.clone())
    fh6.run_consensus()
    assert len(fh6.consensus_events()) > 0


def test_fd_reverse_matches_chain_counts():
    """Both fork fd strategies (reverse level scan vs chain-view compare-
    count) must produce identical tensors."""
    import jax
    import numpy as np

    from babble_tpu.ops import forks as F

    dag = random_byzantine_dag(7, 300, seed=9, fork_rate=0.08)
    fh = ForkHashgraph(dag.participants, k=2)
    for ev in dag.events:
        fh.insert_event(ev.clone())
    cfg, _ = fh._run()
    batch = fh.dag.build_batch(cfg)
    la = jax.jit(lambda b: F._la_scan(cfg, b))(batch)
    a = np.asarray(jax.jit(lambda b: F._fd_reverse(cfg, b))(batch))
    c = np.asarray(jax.jit(lambda b: F._fd_chains(cfg, b, la))(batch))
    assert (a == c).all(), f"{int((a != c).sum())} fd mismatches"


@pytest.mark.parametrize("seed,tight", [(3, False), (9, True), (21, True)])
def test_rounds_closure_matches_level_scan(seed, tight):
    """_rounds_closure (the per-round closure iteration that replaced the
    level scan for speed) must agree with _rounds_scan bit-for-bit —
    including at TIGHT r_cap = max_round + 1, the capacity where an
    off-by-one in the closure's loop bound silently dropped the top
    round (caught in review; this test is the regression anchor)."""
    import functools

    import jax
    import numpy as np

    from babble_tpu.ops import forks as F

    dag = random_byzantine_dag(9, 400, seed=seed, fork_rate=0.06)
    fh = ForkHashgraph(dag.participants, k=2)
    for ev in dag.events:
        fh.insert_event(ev.clone())
    cfg, _ = fh._run()

    def run(cfg):
        batch = fh.dag.build_batch(cfg)
        la = jax.jit(lambda b: F._la_scan(cfg, b))(batch)
        det = jax.jit(lambda b, l: F._detect(cfg, b, l))(batch, la)
        fdet = jax.jit(lambda b, d: F._first_det(cfg, b, d))(batch, det)
        fd = jax.jit(lambda b: F._fd_reverse(cfg, b))(batch)
        helper = jax.jit(lambda b, f, fr: F._helper(cfg, b, f, fr))(
            batch, fd, fdet
        )
        scan = jax.jit(functools.partial(F._rounds_scan, cfg))(
            batch, la, det, helper
        )
        clos = jax.jit(functools.partial(F._rounds_closure, cfg))(
            batch, la, det, helper
        )
        return scan, clos

    scan, clos = run(cfg)
    if tight:
        cfg = cfg._replace(r_cap=int(scan[3]) + 1)
        scan, clos = run(cfg)
    for name, a, b in zip(("round", "witness", "wslot", "max_round"),
                          scan, clos):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=name
        )
    assert int(scan[3]) >= 1


def test_windowed_fork_engine_matches_unevicted():
    """Rolling-window byzantine engine (VERDICT r3 weak #4): streaming
    a byzantine DAG through an auto-compacting ForkHashgraph must
    produce the identical committed order, rounds and receive rounds as
    the unevicted engine — seeds pin retained rounds/witness across
    evictions, chain-index values stay absolute, and the fd-safety
    bound keeps median inputs resolvable."""
    dag = random_byzantine_dag(6, 600, seed=11, fork_rate=0.05)
    plain = ForkHashgraph(dag.participants, k=2)
    rolled = ForkHashgraph(dag.participants, k=2, auto_compact=True,
                           round_margin=1, seq_window=6, compact_min=16)

    chunks = 6
    step = (len(dag.events) + chunks - 1) // chunks
    committed_plain = []
    committed_rolled = []
    for i in range(chunks):
        for ev in dag.events[i * step:(i + 1) * step]:
            plain.insert_event(ev)
            # separate Event objects for the rolled engine: the two
            # engines stamp round_received on commit
            w = rolled.read_wire_info(plain.to_wire(ev))
            rolled.insert_event(w)
        committed_plain += [
            (e.hex(), e.round_received, e.consensus_timestamp)
            for e in plain.run_consensus()
        ]
        committed_rolled += [
            (e.hex(), e.round_received, e.consensus_timestamp)
            for e in rolled.run_consensus()
        ]

    assert rolled.dag.evicted > 0, "window never rolled"
    assert committed_rolled == committed_plain
    assert rolled._lcr_cache == plain._lcr_cache
    assert rolled.max_round() == plain.max_round()
    # rounds of still-live events agree (absolute numbering)
    for s in range(len(rolled.dag.events)):
        x = rolled.dag.events[s].hex()
        assert rolled.round(x) == plain.round(x), x
    # the gossip clock stays absolute across eviction
    assert rolled.known() == plain.known()


def test_laggard_chains_block_unsafe_eviction():
    """ADVICE r4 medium #1: lcr advances on a supermajority and can
    outrun laggard chains.  Two creators that gossip only with each
    other stay at low rounds while the fast group's lcr climbs; when
    they finally merge back, their events legitimately get LOW rounds,
    and assigning those needs the low-round witnesses of the fast
    creators.  A windowed replica that evicted those witnesses would
    compute different rounds than an unevicted one — consensus
    divergence across differently-windowed replicas.  maybe_compact's
    round-consistency gate (max evicted round < min retained round)
    must keep the two engines bit-identical."""
    import numpy as np

    from babble_tpu.core.event import new_event

    n, n_fast = 9, 7            # 7 >= 2*9//3 + 1: fast supermajority
    rng = np.random.default_rng(5)

    def fake_pub(i):
        return b"\x04" + i.to_bytes(32, "big") + bytes(32)

    participants = {("0x" + fake_pub(i).hex().upper()): i for i in range(n)}
    pubs = [fake_pub(i) for i in range(n)]
    heads, seqs = [None] * n, [0] * n
    events = []
    t = [0]

    def mint(recv, send):
        t[0] += 1
        ts = 1_700_000_000_000_000_000 + t[0] * 2_000_000
        parents = ("", "") if heads[recv] is None else (
            heads[recv], heads[send])
        ev = new_event([], parents, pubs[recv], seqs[recv], timestamp=ts)
        ev.r = int(rng.integers(1, 1 << 62))
        ev.s = int(rng.integers(1, 1 << 62))
        events.append(ev)
        heads[recv] = ev.hex()
        seqs[recv] += 1

    for i in range(n):
        mint(i, i)              # roots
    for step in range(700):
        recv = int(rng.integers(0, n_fast))
        send = int(rng.integers(0, n_fast - 1))
        if send >= recv:
            send += 1
        mint(recv, send)        # fast group gossips among itself
        if step % 60 == 30:
            mint(7, 8)          # laggards whisper to each other only
        if step % 60 == 45:
            mint(8, 7)
    mint(7, 8)                  # the late laggard merge (low round)
    mint(0, 7)                  # fast group finally hears the laggards
    for _ in range(60):
        recv = int(rng.integers(0, n_fast))
        send = int(rng.integers(0, n_fast - 1))
        if send >= recv:
            send += 1
        mint(recv, send)

    plain = ForkHashgraph(participants, k=2)
    rolled = ForkHashgraph(participants, k=2, auto_compact=True,
                           round_margin=1, seq_window=4, compact_min=8)
    committed_plain, committed_rolled = [], []
    chunk = 80
    for i in range(0, len(events), chunk):
        for ev in events[i:i + chunk]:
            plain.insert_event(ev)
            rolled.insert_event(rolled.read_wire_info(plain.to_wire(ev)))
        committed_plain += [
            (e.hex(), e.round_received) for e in plain.run_consensus()
        ]
        committed_rolled += [
            (e.hex(), e.round_received) for e in rolled.run_consensus()
        ]

    assert plain.max_round() >= 4, "fast group never outran the laggards"
    assert committed_rolled == committed_plain
    assert rolled._lcr_cache == plain._lcr_cache
    # every live event's round matches the unevicted engine — including
    # the late merge events whose rounds sit far below lcr
    for s in range(len(rolled.dag.events)):
        x = rolled.dag.events[s].hex()
        assert rolled.round(x) == plain.round(x), x


def test_fork_pipeline_sentinel_rows_stay_sentinel():
    """Regression for the ISSUE-12 ``partition-spec-coverage`` findings:
    the fork kernels restored their sentinel/dump rows with
    static-index ``.at[cap].set()`` writes — which lower to
    dynamic-update-slices whose per-shard start clamps under SPMD
    partitioning and corrupts earlier shards once the pipeline runs
    through make_sharded_fork_step (ops/state.py set_sentinel
    docstring; observed on ce/cnt for the honest pipeline).  The
    rewritten elementwise restores must leave every sentinel row
    exactly sentinel-valued; output parity with the oracle is pinned
    by the differential tests above."""
    import jax
    import numpy as np

    from babble_tpu.ops import forks as F

    dag = random_byzantine_dag(6, 220, seed=4, fork_rate=0.1)
    fh = ForkHashgraph(dag.participants, k=2)
    for ev in dag.events:
        fh.insert_event(ev.clone())
    cfg, _ = fh._run()
    batch = fh.dag.build_batch(cfg)

    la = np.asarray(jax.jit(lambda b: F._la_scan(cfg, b))(batch))
    fd = np.asarray(jax.jit(lambda b: F._fd_reverse(cfg, b))(batch))
    assert (la[cfg.e_cap] == -1).all()
    assert (fd[cfg.e_cap] == np.iinfo(np.int32).max).all()

    out = F.fork_pipeline(cfg, batch)
    assert int(np.asarray(out.round)[cfg.e_cap]) == -1
    assert not bool(np.asarray(out.witness)[cfg.e_cap])
    assert (np.asarray(out.wslot)[cfg.r_cap] == -1).all()


def test_fork_engine_clamps_lying_timestamps():
    """Regression for the PR-16 parity gap: fork ingestion routes
    through the same per-creator effective-timestamp clamp as the
    fused/wide engines (core/dag.py clamp_eff_ts), so a lying-clock
    creator cannot drag the round-received medians more than one clamp
    window forward.  The oracle mirrors the clamp (differential stays
    the ground truth) and the clamped values survive a snapshot
    round-trip."""
    import numpy as np

    from babble_tpu.core.event import new_event
    from babble_tpu.store.checkpoint import load_snapshot, snapshot_bytes

    n, liar = 4, 3
    lie_ns = 3_600_000_000_000      # claims one hour in the future
    rng = np.random.default_rng(11)

    def fake_pub(i):
        return b"\x04" + i.to_bytes(32, "big") + bytes(32)

    participants = {("0x" + fake_pub(i).hex().upper()): i for i in range(n)}
    pubs = [fake_pub(i) for i in range(n)]
    heads, seqs = [None] * n, [0] * n
    events = []
    t = [0]

    def mint(recv, send):
        t[0] += 1
        ts = 1_700_000_000_000_000_000 + t[0] * 2_000_000
        if recv == liar and heads[recv] is not None:
            ts += lie_ns
        parents = ("", "") if heads[recv] is None else (
            heads[recv], heads[send])
        ev = new_event([], parents, pubs[recv], seqs[recv], timestamp=ts)
        ev.r = int(rng.integers(1, 1 << 62))
        ev.s = int(rng.integers(1, 1 << 62))
        events.append(ev)
        heads[recv] = ev.hex()
        seqs[recv] += 1

    for i in range(n):
        mint(i, i)
    for _ in range(140):
        recv = int(rng.integers(0, n))
        send = int(rng.integers(0, n - 1))
        if send >= recv:
            send += 1
        mint(recv, send)

    fo = ForkOracle(participants)
    fh = ForkHashgraph(participants, k=2)
    _fill(type("D", (), {"events": events})(), fo, fh)
    committed_h = fh.run_consensus()
    committed_o = fo.run_consensus()

    dag = fh.dag
    clamped = 0
    for s, ev in enumerate(dag.events):
        eff, claimed = dag.eff_ts[s], ev.body.timestamp
        # the oracle's mirror is bit-identical per event
        assert fo._eff_ts[ev.hex()] == eff, ev.hex()[:10]
        if participants[ev.creator] == liar and ev.body.index > 0:
            # a lie is admitted at most one clamp window ahead of the
            # parents; a persistent liar drifts at W per event, not
            # instantly (early lies MUST be cut down)
            if eff < claimed:
                clamped += 1
        else:
            # honest events only ever get raised (parent monotonicity)
            assert eff >= claimed
    assert clamped > 0, "generator produced no lying events"

    # the committed order AND the consensus timestamps stay differential
    assert [(e.hex(), e.consensus_timestamp) for e in committed_h] == \
        [(e.hex(), e.consensus_timestamp) for e in committed_o]
    assert committed_h, "no events reached consensus"

    # clamp state survives the fast-forward snapshot seam
    fh2 = load_snapshot(snapshot_bytes(fh), verify_events=False)
    assert fh2.dag.eff_ts == dag.eff_ts

"""Fixture: wire codecs transcoding on the event loop (codec-on-loop).

Big msgpack frames encoded/decoded inside a coroutine stall every other
RPC and heartbeat for the duration; the sanctioned route is
net/codec.py (size-gated off-loop transcode) or a run_in_executor
closure.
"""

import struct

import msgpack
import msgpack as mp

_HDR = struct.Struct(">BII")


def build_snapshot(state):
    # sync helper reaching msgpack: callers inside coroutines are the
    # violation, this function itself is fine
    return msgpack.packb(state, use_bin_type=True)


class Transport:
    async def send(self, writer, state):
        body = msgpack.packb(state, use_bin_type=True)  # MARK: codec-on-loop
        writer.write(body)

    async def send_aliased(self, writer, state):
        body = mp.packb(state, use_bin_type=True)  # MARK: codec-on-loop
        writer.write(body)

    async def recv(self, reader):
        payload = await reader.read(65536)
        return msgpack.unpackb(payload, raw=False)  # MARK: codec-on-loop

    async def send_snapshot(self, writer, state):
        body = build_snapshot(state)  # MARK: codec-on-loop
        writer.write(body)

    async def send_command(self, writer, req):
        # duck-typed wire command: the graph can't resolve it, the name
        # heuristic catches it
        body = req.pack()  # MARK: codec-on-loop
        writer.write(body)

    async def header_is_fine(self, writer, rid, ln):
        # clean: struct.Struct header codecs are a few fixed bytes
        writer.write(_HDR.pack(0, rid, ln))

    async def offload_is_fine(self, loop, state):
        # clean: the codec runs in an executor-bound closure — the
        # correct pattern, pruned from this coroutine's schedule
        def work():
            return msgpack.packb(state, use_bin_type=True)

        return await loop.run_in_executor(None, work)

    def sync_path(self, state):
        # clean: not a coroutine — bulk/offline paths may pack inline
        return msgpack.packb(state, use_bin_type=True)

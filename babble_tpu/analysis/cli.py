"""babble-lint CLI: ``python -m babble_tpu.analysis [paths...]``.

Exit status is the contract CI keys off: 0 = clean, 1 = findings,
2 = usage error.  ``--format=json`` emits a machine-readable finding
list (one array, not JSONL) for tooling; text format is
``path:line:col: rule: message`` — the same shape compilers use, so
editors and CI annotators parse it for free.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from . import ALL_RULES
from .engine import run_paths


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m babble_tpu.analysis",
        description="babble-lint: repo-native static analysis for JAX "
                    "tracer safety, asyncio races and consensus "
                    "invariants.",
    )
    parser.add_argument(
        "paths", nargs="*", default=["babble_tpu"],
        help="files or directories to check (default: babble_tpu)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (default: text)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    parser.add_argument(
        "--rules", default=None, metavar="RULE[,RULE...]",
        help="run only the named rules (default: all)",
    )
    args = parser.parse_args(argv)

    rules = list(ALL_RULES)
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        known = {r.name for r in rules}
        unknown = wanted - known
        if unknown:
            print(f"unknown rule(s): {sorted(unknown)}", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in wanted]

    if args.list_rules:
        for r in sorted(ALL_RULES, key=lambda r: r.name):
            print(f"{r.name}: {r.description}")
        return 0

    # a path that matches nothing is a usage error, not a clean run —
    # exit 0 must mean "these files were checked and are clean", or a
    # typo'd CI invocation stays green forever
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"no such file or directory: {missing}", file=sys.stderr)
        return 2

    findings = run_paths(args.paths, rules,
                         known_rules={r.name for r in ALL_RULES})
    if args.format == "json":
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        if findings:
            print(f"\n{len(findings)} finding(s)", file=sys.stderr)
    return 1 if findings else 0

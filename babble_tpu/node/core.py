"""Core: one participant's consensus state (reference node/core.go:30-257).

Wraps a TpuHashgraph with the node's signing key, tracks the head of the
node's own event chain, computes gossip diffs from Known vector clocks, and
applies incoming syncs by inserting peer events and creating a new signed
self-event whose parents are (own head, peer head) carrying the pooled
transactions.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

from ..consensus.engine import TpuHashgraph
from ..core.event import Event, WireEvent, new_event
from ..crypto.keys import KeyPair
from ..membership.quorum import sync_quorum
from ..obs import Registry
from ..wal import WriteAheadLog


def _mark_chain_verified(events: List[Event]) -> None:
    """Signature elision over contiguous self-parent chains.

    For each creator, the batch (already topologically ordered, so a
    creator's events appear oldest-first) is split into runs where each
    event's ``self_parent`` is the previous event's full id and the
    index is contiguous.  The newest event of a run >= 2 is verified
    HERE, upfront; success marks the entire run ``chain_verified`` (the
    insert paths then skip per-event ECDSA).  Soundness: an event's id
    hashes body+signature, and the id is inside the successor's SIGNED
    body — so the head signature authenticates every predecessor byte
    transitively, and a fabricated prefix event would break the hash
    chain it claims membership of.  A failed head verify marks nothing:
    the per-event insert checks then reject exactly as before.  Runs of
    one (idle fleets) keep the plain per-event verify."""
    runs: Dict[str, List[Event]] = {}
    for ev in events:
        run = runs.setdefault(ev.creator, [])
        if run and not (ev.self_parent == run[-1].hex()
                        and ev.index == run[-1].index + 1):
            if len(run) >= 2 and run[-1].verify():
                for e in run:
                    e.chain_verified = True
            runs[ev.creator] = run = []
        run.append(ev)
    for run in runs.values():
        if len(run) >= 2 and run[-1].verify():
            for e in run:
                e.chain_verified = True


class Core:
    def __init__(
        self,
        core_id: int,
        key: KeyPair,
        participants: Dict[str, int],
        commit_callback: Optional[Callable[[List[Event]], None]] = None,
        engine: Optional[TpuHashgraph] = None,
        e_cap: int = 4096,
        cache_size: Optional[int] = None,
        seq_window: Optional[int] = None,
        byzantine: bool = False,
        fork_k: int = 2,
        fork_caps: Optional[tuple] = None,
        wide: bool = False,
        wide_caps: Optional[tuple] = None,
        registry: Optional[Registry] = None,
        wal: Optional[WriteAheadLog] = None,
        kernel_class: str = "auto",
        inactive_rounds: Optional[int] = 32,
        lineage=None,
        phase_probe: bool = False,
        packed_votes: bool = True,
        frontier: bool = True,
    ):
        self.id = core_id
        self.kernel_class = kernel_class
        # kernel working-set diet (ROADMAP item 4): both knobs are
        # bit-parity-preserving pins — packed popcount vote tallies and
        # the event-axis frontier bucket on the fused latency kernel
        self.packed_votes = packed_votes
        self.frontier = frontier
        # attribution plane (ISSUE 11): the owning node's commit-lineage
        # recorder.  Hooks live at the two places only the Core can see
        # — the mint (tx -> event hash join pivot) and the peer insert.
        # None (or a disabled recorder) makes every hook a no-op.
        self.lineage = lineage
        self.phase_probe = phase_probe
        self.key = key
        self.pub_hex = key.pub_hex
        self.participants = participants
        # Membership plane: a node whose key is not (yet) in the epoch's
        # peer set runs as an OBSERVER — it syncs, validates and commits
        # like any replica but never mints, because no honest peer would
        # accept an event from a non-member.  A committed join naming
        # our key flips this (adopt_membership); a committed leave sets
        # the retired flag the same way.
        self._observer = key.pub_hex not in participants
        self._retired_self = False
        self.registry = registry
        # event-timestamp clock, overridable for deterministic replay:
        # the chaos scenario runner installs a seeded logical clock here
        # so event bodies (and therefore hashes, signatures and
        # timestamp-median tie-breaks) are identical across runs
        self.now_ns: Callable[[], int] = time.time_ns
        if engine is not None:
            # an injected engine is authoritative: the mode flag must
            # match its type, or diff()/head restore would misbehave —
            # and so is its PARTICIPANT set: a checkpoint restored from
            # a later epoch legitimately differs from the boot peer
            # list (membership plane), so the engine's epoch ledger
            # wins and observer status is recomputed against it
            from ..consensus.fork_engine import ForkHashgraph

            self.hg = engine
            byzantine = isinstance(engine, ForkHashgraph)
            participants = engine.participants
            self.participants = participants
            self._observer = self.pub_hex not in participants
            self.id = participants.get(self.pub_hex, -1)
        elif byzantine:
            # fork-aware live mode: equivocations are accepted, detected
            # and discounted instead of rejected (ops/forks.py); gossip
            # ships the self-contained FullWireEvent form because the
            # compact (creatorID, index) references are ambiguous under
            # forks.  Batch execution per consensus tick over a rolling
            # window (fork_engine.maybe_compact) keeps per-tick cost and
            # jit shapes bounded forever.
            from ..consensus.fork_engine import ForkHashgraph

            self.hg = ForkHashgraph(
                participants, k=fork_k,
                commit_callback=commit_callback,
                verify_signatures=True,
                auto_compact=bool(cache_size),
                seq_window=min(seq_window or cache_size or 256, 256),
                compact_min=max((cache_size or 256) // 4, 32),
                initial_caps=fork_caps,
            )
        elif wide:
            # column-blocked rolling-window engine (the wide-N memory
            # layout) behind the same Core surface; capacities are a
            # boot-time contract — the engine compacts instead of
            # growing (consensus/wide_engine.py)
            from ..consensus.wide_engine import WideHashgraph

            cs = cache_size or 4096
            wc = wide_caps or (max(8 * cs, 4096), 256, 64)
            self.hg = WideHashgraph(
                participants, commit_callback=commit_callback,
                e_cap=wc[0], s_cap=wc[1], r_cap=wc[2],
                auto_compact=True,
                seq_window=min(seq_window or cs, wc[1] // 2),
                round_margin=1,
                consensus_window=2 * cs,   # commit log bounded too
                registry=registry,
            )
        else:
            # The live path runs with rolling windows on (auto_compact):
            # memory stays bounded and peers that fall behind the
            # cache_size window get TooLateError through the sync path,
            # like the reference's rolling caches (caches.go:45-76).
            self.hg = TpuHashgraph(
                participants, commit_callback=commit_callback, e_cap=e_cap,
                auto_compact=bool(cache_size),   # 0/None = unbounded history
                seq_window=seq_window or cache_size or 256,
                consensus_window=2 * cache_size if cache_size else None,
                # live semantics: a round's fame (and therefore its prn
                # whitening and cts medians) freezes only once every
                # chain's head round has passed it — the witness-set
                # finality gate (ops/wide.py complete=False ported to
                # the fused path; ROADMAP premature intra-round finality)
                finality_gate=True,
                kernel_class=kernel_class,
                # per-creator eviction (ISSUE 8): a peer silent for
                # this many decided rounds stops pinning the window
                inactive_rounds=inactive_rounds,
                packed_votes=packed_votes,
                frontier=frontier,
            )
        self.byzantine = byzantine
        self._apply_live_engine_policy()
        if engine is not None:
            # a checkpoint-restored engine was built before this node's
            # registry existed: rebind its instruments (wide-engine
            # flush/stage histograms) or their series silently vanish
            # from /metrics for the whole resumed run
            self._rebind_engine_registry()
        # byzantine-mode per-event insert failures (ADVICE r3): counted,
        # not raised — surfaced via insert_failures for stats/tests
        self.insert_failures = 0
        self.last_insert_error: Optional[str] = None
        #: merge mints skipped because the sync partner's head was
        #: minted by a creator retired in the current epoch
        self.retired_merge_skips = 0
        # self-stabilizing gossip (ADVICE r3 medium, layer 3): count-skip
        # diffs can hide the symmetric difference under equivocation.
        # The fork engine's tip exchange makes a hidden divergence
        # surface as a parent-not-known failure on an event of the
        # DIVERGED creator; each such failure doubles that creator's
        # backoff, and known() under-advertises that creator's count by
        # it — so diffs reach ever deeper into its chain until the
        # fork's shared prefix arrives and the branch materializes
        # (duplicates are dropped by hash).  The backoff is per-creator
        # and resets only when a NEW event of that creator inserts
        # (progress), so interleaved healthy syncs cannot wipe it:
        # divergence depth d heals in ~log2(d) failing syncs total.
        self._creator_backoff: Dict[int, int] = {}
        # Durability plane (wal/): the write-ahead log is replayed on
        # top of whatever engine we booted with (fresh or checkpoint-
        # restored), so the node resumes at its true head seq and never
        # re-mints a sequence number it already published (ROADMAP
        # crash-recovery amnesia).  _min_next_seq is the mint floor the
        # recovery ladder established; while the engine's own chain sits
        # below it, minting is deferred and gossip/fast-forward restore
        # the published tail first.
        self.wal = wal
        self._wal_own_max = -1
        self._wal_orphans: List[Event] = []
        self._min_next_seq = 0
        # Peer-negotiated seq skip-ahead (the WAL-missing fallback): no
        # durable memory of our own chain exists, so minting waits for a
        # supermajority of peers (counting ourselves) to answer a sync —
        # each applied response merges that peer's view of our chain, so
        # at quorum the engine head IS the max published seq any
        # supermajority member has seen, and _min_next_seq lands one
        # past it.
        self._probing = False
        self._probe_seen: set = set()
        #: transactions of an unrecoverable own-chain suffix discarded
        #: by the last horizon bootstrap (node re-pools them)
        self.last_bootstrap_lost_txs: List[bytes] = []
        # supermajority is 2n//3+1 members counting ourselves, so the
        # probe needs 2n//3 PEER answers — 0 for a single-participant
        # fleet, where our own durable state is the only authority.
        # Routed through the epoch-aware helper: with dynamic
        # membership this count must track the ACTIVE set.
        self._probe_quorum = sync_quorum(self._active_count())
        if wal is not None:
            self._recover_from_wal()
        self.head: str = ""
        self.seq: int = -1
        # A resumed engine (store.load_checkpoint) already holds our chain —
        # pick up where the checkpoint left off.  Observers have no chain.
        if self._observer:
            pass
        elif byzantine:
            own = self.hg.dag.cr_events[participants[self.pub_hex]]
            if own:
                head_ev = self.hg.dag.events[own[-1]]
                self.head = head_ev.hex()
                self.seq = head_ev.index
        else:
            chain = self.hg.dag.chains[participants[self.pub_hex]]
            if chain:
                head_ev = self.hg.dag.events[chain[-1]]
                self.head = head_ev.hex()
                self.seq = head_ev.index
        if wal is not None:
            # the mint floor: one past the newest self-event the WAL
            # (records + head receipt) remembers publishing.  A torn
            # tail may have lost the newest receipt-less records, so a
            # truncated log ALSO probes — re-minting a seq a minority of
            # peers already hold would read as an equivocation.
            self._min_next_seq = max(
                self._wal_own_max + 1, wal.receipt_seq + 1
            )
            # probe whenever recovery cannot vouch for every published
            # seq: missing log, torn tail, or an unclean shutdown under
            # a batched fsync policy (a whole record suffix can be lost
            # at a clean fsync boundary with nothing left to detect)
            self._probing = self._probe_quorum > 0 and wal.needs_probe

        if registry is not None:
            # sampled at scrape time through self.hg so the gauges stay
            # correct across a fast-forward engine swap (bootstrap
            # rebinds self.hg; the callbacks read the live one).  All
            # are host-side mirrors (stats_snapshot) — no device sync on
            # a /metrics scrape.  One cached snapshot serves every gauge
            # of a single exposition pass: the families are read
            # back-to-back, so a short reuse window keeps the exposed
            # mirrors mutually consistent (no torn scrape across a
            # concurrent commit) and builds the snapshot once, not once
            # per gauge.
            snap_cache = {"t": float("-inf"), "v": {}}

            def _snap() -> dict:
                now = time.monotonic()
                if now - snap_cache["t"] > 0.2:
                    snap_cache["v"] = self.hg.stats_snapshot()
                    snap_cache["t"] = now
                return snap_cache["v"]

            for gname, key in (
                ("babble_consensus_events", "consensus_events"),
                ("babble_consensus_transactions", "consensus_transactions"),
                ("babble_undetermined_events", "undetermined_events"),
                ("babble_last_consensus_round", "last_consensus_round"),
                ("babble_evicted_events", "evicted_events"),
                ("babble_live_window_events", "live_window"),
            ):
                registry.gauge(
                    gname, f"host mirror of /Stats {key}",
                ).set_function(lambda k=key: _snap().get(k, 0))
            registry.gauge(
                "babble_insert_failures",
                "per-event insert failures tolerated in byzantine mode",
            ).set_function(lambda: self.insert_failures)
            registry.gauge(
                "babble_retired_merge_skips",
                "merge mints skipped because the sync partner's head "
                "was minted by a retired creator",
            ).set_function(lambda: self.retired_merge_skips)
            if byzantine:
                registry.gauge(
                    "babble_forked_creators",
                    "creators with a detected live equivocation",
                ).set_function(lambda: _snap().get("forked_creators", 0))

    # ------------------------------------------------------------------
    # durability (wal/): recovery, the mint floor, the seq probe

    def _recover_from_wal(self) -> None:
        """Replay the WAL tail on top of the booted engine (recovery
        already truncated it at the first torn/corrupt record).  Replay
        is best-effort per event: a record whose parents predate a
        restored checkpoint's window simply fails to insert — the fleet
        re-delivers through gossip/fast-forward — but every surviving
        SELF record still raises the mint floor, insertable or not,
        because those seqs were published."""
        replayed = 0
        for ev in self.wal.recovered_events:
            if ev.creator == self.pub_hex:
                self._wal_own_max = max(self._wal_own_max, ev.index)
            if ev.hex() in self.hg.dag.slot_of:
                continue
            try:
                self.hg.insert_event(ev)
                replayed += 1
            except ValueError:
                if ev.creator == self.pub_hex:
                    # a durably-logged SELF event whose parents predate
                    # the restored window (e.g. the checkpoint rotted
                    # away): it raised the mint floor above, so it must
                    # stay retryable — once gossip restores its parents,
                    # re-inserting the SAME signed event un-wedges
                    # minting without any equivocation risk.  Dropping
                    # it here would leave the floor unreachable and the
                    # node mute forever.
                    self._wal_orphans.append(ev)
                continue
        self.wal.mark_replayed(replayed)

    def _wal_append(self, event: Event) -> None:
        if self.wal is not None:
            self.wal.append(event)

    @property
    def probing(self) -> bool:
        return self._probing

    @property
    def min_next_seq(self) -> int:
        return self._min_next_seq

    def mint_blocked(self) -> bool:
        """True while creating a self-event could re-mint a published
        sequence number — or while this node is not a member of the
        current epoch's peer set at all (observer waiting on its join,
        or retired by a committed leave): either the seq probe is still
        negotiating, or the engine's view of our own chain sits below
        the recovery ladder's mint floor (gossip / fast-forward will
        restore the published tail, at which point minting resumes
        naturally)."""
        if self._observer or self._retired_self:
            return True
        return self._probing or self.seq + 1 < self._min_next_seq

    # ------------------------------------------------------------------
    # membership plane (ISSUE 9)

    def _active_count(self) -> int:
        """Active members of the current epoch (retired columns
        excluded) — the n every quorum is computed against."""
        retired = getattr(getattr(self.hg, "cfg", None), "retired", ())
        return len(self.participants) - len(retired)

    def refresh_quorums(self) -> None:
        """Re-derive every membership-dependent threshold after an
        epoch transition (or an engine swap that carried one)."""
        self._probe_quorum = sync_quorum(self._active_count())

    def adopt_membership(self) -> None:
        """A committed join named OUR key: we are a validator from the
        epoch boundary on.  Idempotent (checkpoint-restored nodes
        replay their ledger at boot)."""
        cid = self.participants.get(self.pub_hex)
        if cid is None:
            return
        self.id = cid
        self._observer = False
        chain = self.hg.dag.chains[cid]
        if chain and chain.window:
            head_ev = self.hg.dag.events[chain[-1]]
            self.head = head_ev.hex()
            self.seq = head_ev.index
        self.refresh_quorums()

    def retire_membership(self) -> None:
        """A committed leave named OUR key: stop minting permanently
        (the node keeps serving as an observer — its history remains
        useful to the fleet until it shuts down)."""
        self._retired_self = True
        self.refresh_quorums()

    def probe_note(self, peer: str) -> bool:
        """One sync response from ``peer`` was applied while probing.
        Returns True exactly when this response completed the quorum:
        the engine head now reflects the max seq a supermajority
        (counting ourselves) has seen of us, so minting resumes one
        past it."""
        if not self._probing:
            return False
        self._probe_seen.add(peer)
        if len(self._probe_seen) < self._probe_quorum:
            return False
        self._probing = False
        self._min_next_seq = max(self._min_next_seq, self.seq + 1)
        return True

    def _adopt_own_event(self, ev: Event) -> None:
        """A peer (or snapshot) delivered one of OUR published events
        that the crash lost: advance head/seq so the next mint extends
        the true chain instead of re-minting its index."""
        if ev.creator == self.pub_hex and ev.index > self.seq:
            self.head = ev.hex()
            self.seq = ev.index

    def _retry_wal_orphans(self) -> None:
        """Re-attempt the recovered self events whose first insert
        failed (parents were outside the restored window).  Called
        after each sync's peer inserts: once gossip has restored the
        missing ancestry, the orphan inserts, head/seq adopt it, and
        the mint floor it pinned becomes reachable again."""
        if not self._wal_orphans:
            return
        rest: List[Event] = []
        for ev in sorted(self._wal_orphans, key=lambda e: e.index):
            if ev.hex() in self.hg.dag.slot_of:
                self._adopt_own_event(ev)
                continue
            try:
                self.hg.insert_event(ev)
                self._adopt_own_event(ev)
            except ValueError:
                rest.append(ev)
        self._wal_orphans = rest

    # ------------------------------------------------------------------

    def _apply_live_engine_policy(self) -> None:
        """Live-path engine semantics a restored/injected fused engine
        must adopt: the witness-set finality gate (checkpoints and
        fast-forward snapshots don't serialize it — it is a property of
        the LIVE path, not of the DAG state) and this core's kernel-
        class pin.  Both are per-call static arguments on the compiled
        entries, so flipping the attributes is safe at any flush
        boundary."""
        if (isinstance(self.hg, TpuHashgraph)
                and type(self.hg).KERNEL_SPLIT):
            self.hg.finality_gate = True
            self.hg.kernel_class = self.kernel_class
            self.hg.phase_probe = self.phase_probe
            # diet pins (kernel working-set diet): an adopted snapshot
            # carries the peer's packed flag in its cfg — override with
            # this core's policy (bit-parity either way, but the
            # compiled-program universe should follow local config)
            self.hg.frontier = self.frontier
            if self.hg.cfg.packed != self.packed_votes:
                self.hg.cfg = self.hg.cfg._replace(
                    packed=self.packed_votes
                )
                self.hg._aot = {}

    def _rebind_engine_registry(self) -> None:
        """Point the current engine's instruments at this core's
        registry.  A bootstrap-restored or checkpoint-resumed engine was
        constructed with a private registry (load_snapshot knows nothing
        of the node); without this rebind its flush/stage histograms
        keep observing into that orphan and the series drop off
        /metrics after every fast-forward engine swap."""
        if self.registry is None:
            return
        rebind = getattr(self.hg, "rebind_registry", None)
        if rebind is not None:
            rebind(self.registry)

    def bootstrap(self, engine: TpuHashgraph) -> None:
        """Replace the consensus engine with a fast-forward snapshot (the
        catch-up path, node.py): adopt the peer's windowed state and pick
        our own chain back up from whatever the snapshot knows of us.

        Validates before swapping so a bad snapshot can't leave the Core
        half-migrated.  The eviction policy keeps every creator's last
        seq_window events, so a non-empty chain always has a live tail;
        an empty window despite a non-zero count means a corrupt snapshot.

        If our local chain is *ahead* of the snapshot's view of us (our
        newer events already reached other peers before the partition), we
        must not roll head/seq back — the next self-event would reuse an
        index and read as an equivocation, permanently poisoning our gossip
        (ADVICE r2 medium).  The local tail beyond the snapshot is replayed
        into the new engine; if any of it is not insertable there (an
        other-parent outside the snapshot window), bootstrap refuses and
        the old engine stays in place."""
        from ..store.checkpoint import engine_mode

        # full KIND check, not just byzantine-ness: a wide core must
        # not silently adopt a fused snapshot (abandoning the memory
        # layout the operator configured) or vice versa
        if engine_mode(engine) != engine_mode(self.hg):
            raise ValueError(
                f"bootstrap engine kind '{engine_mode(engine)}' does "
                f"not match this core's '{engine_mode(self.hg)}'"
            )
        # flush_fallbacks backs a *_total metric series read through
        # self.hg: carry the old engine's count across the swap or the
        # monotone counter goes backwards at every fast-forward
        if hasattr(engine, "flush_fallbacks"):
            engine.flush_fallbacks = (
                getattr(engine, "flush_fallbacks", 0)
                + getattr(self.hg, "flush_fallbacks", 0)
            )
        if self.byzantine:
            self._bootstrap_fork(engine)
            self._note_ff_adopted()
            return
        # Membership plane: the adopted engine's epoch ledger is
        # authoritative (validate_ff_snapshot verified its membership
        # chain against our trusted set before we got here) — rebind
        # our participant view and observer status to it.  A joiner
        # bootstrapping through fast-forward becomes a member exactly
        # when the snapshot's epoch says so.
        self.participants = engine.participants
        self._observer = self.pub_hex not in engine.participants
        self.id = engine.participants.get(self.pub_hex, -1)
        if self._observer:
            # not (yet) a member: adopt the window wholesale; minting
            # stays blocked until a later epoch admits us.  The WAL
            # receipt/prune and the lost-tx reset still apply — stale
            # records predating the adopted window would fail replay
            # on the next restart, and a leftover lost-tx list from an
            # earlier member-path bootstrap must not be re-pooled
            self.hg = engine
            self.head = ""
            self.seq = -1
            self.last_bootstrap_lost_txs = []
            self.refresh_quorums()
            self._apply_live_engine_policy()
            self._rebind_engine_registry()
            self._note_ff_adopted()
            return
        cid = self.participants[self.pub_hex]
        chain = engine.dag.chains[cid]
        horizon = engine.dag.evicted_heads.get(cid)
        if chain and not chain.window:
            # Per-creator eviction (ISSUE 8): the fleet evicted our
            # ENTIRE retained tail during the outage — legitimate
            # exactly when the snapshot records our eviction horizon at
            # the chain's logical tip.  The horizon's (index, hex) IS
            # the fleet's view of our published chain head: we resume
            # from it (continuation events are insertable fleet-wide
            # via the horizon rule in HostDag.insert).  A window-less
            # chain with no matching horizon is still a corrupt
            # snapshot.
            if horizon is None or horizon[0] != len(chain) - 1:
                raise ValueError(
                    "snapshot window holds none of our own chain tail "
                    "and records no matching eviction horizon"
                )
            snap_seq = horizon[0]
        else:
            snap_seq = engine.dag.events[chain[-1]].index if chain else -1
        lost_txs: List[bytes] = []
        tail_lost = False
        if self.seq > snap_seq:
            if chain and not chain.window:
                # Horizon rejoin: replay our local tail as far as the
                # adopted window allows (the first event rides the
                # continuation rule).  A suffix whose ancestry the
                # whole fleet evicted is UNRECOVERABLE — no other peer
                # can serve a snapshot that still holds it — so
                # refusing here (the strict path below) would wedge the
                # node forever.  The suffix is discarded, its
                # transactions surface for re-mint, and the seq probe
                # re-arms: minting stays deferred until a supermajority
                # of sync partners confirm nobody holds a higher seq of
                # ours, so a fresh event can reuse the first lost index
                # without equivocation risk (same residual trust as the
                # WAL-missing probe).
                lost_txs, tail_lost = self._replay_continuation_tail(
                    engine, cid, snap_seq
                )
            else:
                # in-window tail: the snapshot peer is merely behind —
                # a refusal keeps the old engine and a later snapshot
                # (or plain gossip) reconciles losslessly
                self._replay_own_tail(engine, cid, snap_seq)
        chain = engine.dag.chains[cid]
        if chain and chain.window:
            head_ev = engine.dag.events[chain[-1]]
            self.hg = engine
            self.head = head_ev.hex()
            self.seq = head_ev.index
        elif chain:
            # window still empty after reconciliation: our local head
            # is at or below the fleet's horizon — adopt the horizon as
            # our chain tip.  Those seqs were published under our key
            # (every peer ordered them before evicting), so the next
            # mint extends at horizon+1 instead of ever re-minting.
            self.hg = engine
            self.head = horizon[1]
            self.seq = horizon[0]
        else:
            # the snapshot knows nothing of us (our pre-partition events
            # never propagated): mint a fresh root so syncs have a head
            self.hg = engine
            self.head = ""
            self.seq = -1
            self.init()
        if tail_lost:
            # unrecoverable suffix discarded: allow the next mint to
            # reuse its first index — guarded by the re-armed probe
            self._min_next_seq = self.seq + 1
            self._probing = self._probe_quorum > 0
            self._probe_seen = set()
        self.last_bootstrap_lost_txs = lost_txs
        self.refresh_quorums()
        self._apply_live_engine_policy()
        self._rebind_engine_registry()
        self._note_ff_adopted()

    def _replay_continuation_tail(
        self, engine: TpuHashgraph, cid: int, snap_seq: int
    ) -> Tuple[List[bytes], bool]:
        """Replay our own events past the adopted snapshot's eviction
        horizon, as far as the new window can resolve them (the first
        rides the continuation insert rule).  Returns ``(lost_txs,
        tail_lost)``: the transactions of the unrecoverable suffix
        (events whose other-parents the whole fleet evicted) so the
        node can re-pool them for a fresh mint, and whether any suffix
        was discarded at all (re-arms the seq probe even when the lost
        events carried no transactions)."""
        old_chain = self.hg.dag.chains[cid]
        lost: List[bytes] = []
        broken = False
        for q in range(snap_seq + 1, self.seq + 1):
            if q < old_chain.start:
                # locally evicted too: nothing left to replay or re-pool
                broken = True
                continue
            ev = self.hg.dag.events[old_chain[q]]
            if not broken:
                try:
                    engine.insert_event(ev)
                    continue
                except ValueError:
                    broken = True
            lost.extend(ev.transactions)
        return lost, broken

    def _note_ff_adopted(self) -> None:
        """WAL-aware fast-forward receipts (PR 5 leftover): the adopted
        snapshot supersedes everything the WAL recorded — replaying
        those records over the new window would just fail on the next
        restart (their ancestry predates the adopted window) while the
        lost head receipt would force a needless seq probe.  Prune the
        records the snapshot now covers and stamp the receipt with the
        adopted head; a crash before the next checkpoint then recovers
        by fast-forwarding again, mint floor intact."""
        if self.wal is not None:
            self.wal.checkpointed(self.seq, self.head)
        self._min_next_seq = max(self._min_next_seq, self.seq + 1)

    def _bootstrap_fork(self, engine) -> None:
        """Byzantine-mode bootstrap (VERDICT r4 missing #5): adopt a
        fork-aware snapshot engine.  Beyond the honest checks, a
        snapshot that records an equivocation by US is refused outright:
        our key never forks, so either the snapshot is corrupt or our
        key is compromised — and replaying our local tail onto a
        diverged view of our own chain would MINT a fork under our
        signature, permanently poisoning our gossip."""
        cid = self.participants[self.pub_hex]
        dag = engine.dag
        if any(dag.br_used[c]
               for c in range(cid * dag.k + 1, (cid + 1) * dag.k)):
            raise ValueError(
                "snapshot records an equivocation by our own key; "
                "refusing bootstrap"
            )
        own = dag.cr_events[cid]
        if not own and dag.cr_evicted[cid] > 0:
            raise ValueError(
                "snapshot window holds none of our own chain tail"
            )
        snap_seq = max(
            (dag.events[s].index for s in own), default=-1
        )
        if self.seq > snap_seq:
            old = self.hg.dag
            by_idx = {
                old.events[s].index: old.events[s]
                for s in old.cr_events[cid]
            }
            tail = []
            for q in range(snap_seq + 1, self.seq + 1):
                ev = by_idx.get(q)
                if ev is None:
                    raise ValueError(
                        f"own-chain tail seq {q} locally evicted; cannot "
                        "reconcile snapshot behind our published chain"
                    )
                tail.append(ev)
            saved = [(ev, ev.topological_index) for ev in tail]
            try:
                for ev in tail:
                    engine.insert_event(ev)
            except Exception as e:
                for ev, ti in saved:
                    ev.topological_index = ti
                raise ValueError(
                    f"snapshot is behind our published chain (local seq "
                    f"{self.seq} > snapshot {snap_seq}) and the tail is "
                    f"not insertable into it: {e}"
                ) from e
        own = dag.cr_events[cid]
        if own:
            tip = max(own, key=lambda s: dag.events[s].index)
            self.hg = engine
            self.head = dag.events[tip].hex()
            self.seq = dag.events[tip].index
        else:
            # the snapshot knows nothing of us: mint a fresh root
            self.hg = engine
            self.head = ""
            self.seq = -1
            self.init()
        self._apply_live_engine_policy()
        self._rebind_engine_registry()

    def _replay_own_tail(
        self, engine: TpuHashgraph, cid: int, snap_seq: int
    ) -> None:
        """Re-insert our own events with index in (snap_seq, self.seq] from
        the current engine into ``engine``.  Raises ValueError (refusing the
        bootstrap) if the tail is locally evicted or not insertable there.
        ``topological_index`` is restored on failure: insert() stamps it
        with the new engine's slots, and the old engine's gossip diff sort
        must stay intact when we keep it."""
        old_chain = self.hg.dag.chains[cid]
        tail = []
        for q in range(snap_seq + 1, self.seq + 1):
            if q < old_chain.start:
                raise ValueError(
                    f"own-chain tail seq {q} locally evicted; cannot "
                    "reconcile snapshot behind our published chain"
                )
            tail.append(self.hg.dag.events[old_chain[q]])
        saved = [(ev, ev.topological_index) for ev in tail]
        try:
            for ev in tail:
                engine.insert_event(ev)
        except Exception as e:
            for ev, ti in saved:
                ev.topological_index = ti
            raise ValueError(
                f"snapshot is behind our published chain (local seq "
                f"{self.seq} > snapshot {snap_seq}) and the tail is not "
                f"insertable into it: {e}"
            ) from e

    def init(self) -> None:
        """Create + insert the node's root event (reference core.go:79-97).
        A no-op while the durability ladder blocks minting (seq probe in
        flight, or the WAL says seq 0 was already published)."""
        if self.mint_blocked():
            return
        ev = new_event([], ("", ""), self.key.pub_bytes, 0,
                       timestamp=self.now_ns())
        self.sign_and_insert_self_event(ev)

    def sign_and_insert_self_event(self, event: Event) -> None:
        event.sign(self.key)
        # write-AHEAD: the event hits the log (fsynced per policy)
        # before the insert that makes it gossipable, so a crash can
        # never forget a seq any peer might have seen.  An insert
        # failure leaves an orphan record; replay dedups it.
        self._wal_append(event)
        self.hg.insert_event(event)
        self.head = event.hex()
        self.seq = event.index
        if self.lineage is not None:
            # the mint record is the tx -> event hash-join pivot
            self.lineage.note_mint(event.hex(), event.transactions)

    def insert_event(self, event: Event) -> None:
        self.hg.insert_event(event)

    # ------------------------------------------------------------------
    # gossip protocol

    def known(self) -> Dict[int, int]:
        """The vector clock this core advertises to sync partners.  In
        byzantine mode, creators with an active gossip backoff (see
        __init__) are under-advertised so hidden set divergences
        eventually resync."""
        k = self.hg.known()
        if self.byzantine and self._creator_backoff:
            # Cap the under-advertisement at our own retained window
            # depth for that creator (ADVICE r4 medium #2): resync
            # material below our window base is committed on both sides
            # (participant_events caps its resend there too), and an
            # advertised count below the PEER's eviction point turns
            # every sync into TooLate — with no byzantine fast-forward
            # that wedges the pair permanently, and the backoff could
            # never reset because no sync ever succeeded.
            k2 = {}
            for cid, c in k.items():
                b = self._creator_backoff.get(cid, 0)
                if b:
                    b = min(b, len(self.hg.dag.cr_events[cid]))
                k2[cid] = max(0, c - b)
            k = k2
        return k

    def reset_gossip_backoff(self) -> None:
        """Drop all per-creator resync backoff.  Called when a sync
        returns too_late: the under-advertised counts fell below the
        peer's rolling window, so deeper probing can only wedge — the
        fast-forward path takes over from there (ADVICE r4 medium #2)."""
        self._creator_backoff.clear()

    def diff(self, known: Dict[int, int]) -> List[Event]:
        """Events we know that the peer doesn't, topologically sorted
        (reference core.go:108-132)."""
        out: List[Event] = []
        src = self.hg if self.byzantine else self.hg.dag
        for pub, cid in self.participants.items():
            skip = known.get(cid, 0)
            for hex_id in src.participant_events(pub, skip):
                out.append(self.hg.dag.events[self.hg.dag.slot_of[hex_id]])
        out.sort(key=lambda e: e.topological_index)
        return out

    def to_wire(self, events: List[Event]) -> List[WireEvent]:
        return [self.hg.to_wire(e) for e in events]

    def from_wire(self, wire_events: List[WireEvent]) -> List[Event]:
        return [self.hg.read_wire_info(w) for w in wire_events]

    def sync(
        self,
        other_head: str,
        wire_events: List[WireEvent],
        payload: List[bytes],
    ) -> bool:
        """Insert peer events, then create the new head (core.go:134-157).

        Byzantine mode inserts per-event instead of all-or-nothing
        (ADVICE r3): one bad event (ForkBudgetError when a creator
        exceeds its fork budget, bad signature, unknown parent) must not
        drop the remaining valid events from OTHER creators in the same
        response, or a single spamming equivocator would permanently
        poison every future sync that includes its events.  Honest mode
        stays strict — there an insert error means a protocol violation
        and the whole sync is rejected (reference core.go:139-146).

        Signature elision (ingress plane): the batch is scanned for
        contiguous self-parent chains per creator; one upfront ECDSA
        verify of each chain's newest event transitively authenticates
        the whole run (the signed body names the predecessor's full
        body+signature hash), so under load per-event verify cost
        divides by the batch depth instead of pacing the fleet."""
        # convert the whole batch upfront (the elision scan needs every
        # hash before the first insert); the overlay resolves compact
        # parent references into the not-yet-inserted batch prefix with
        # the same semantics the old convert-one-insert-one loop had.
        # Conversion is TOLERANT per event (membership plane): a peer
        # one epoch ahead legitimately ships events of a creator we do
        # not know yet, woven into the founders' chains as parents —
        # those convert-fail (unknown creator id / unresolvable ref)
        # and are SKIPPED, which recursively prunes everything built on
        # them (children resolve parents through the overlay or local
        # chains, both of which lack the skipped event).  What survives
        # is exactly the old-epoch-reachable prefix — enough to reach
        # the boundary, apply the transition, and accept the rest on
        # the next exchange.  Without this, one cross-epoch sync wedged
        # the laggard forever.
        from ..common import TooLateError

        overlay: Dict[Tuple[int, int], str] = {}
        events: List[Event] = []
        skipped = 0
        for w in wire_events:
            try:
                ev = self.hg.read_wire_info(w, overlay)
            except (KeyError, IndexError, TooLateError) as e:
                skipped += 1
                self.last_insert_error = f"wire conversion skipped: {e}"
                continue
            creator_cid = self.participants.get(ev.creator)
            if creator_cid is not None:
                overlay[(creator_cid, ev.index)] = ev.hex()
            events.append(ev)
        if skipped:
            self.insert_failures += skipped
        _mark_chain_verified(events)
        for ev in events:
            if ev.hex() in self.hg.dag.slot_of:
                continue
            if self.byzantine:
                cid = self.participants.get(ev.creator)
                try:
                    self.insert_event(ev)
                    self._wal_append(ev)
                    self._adopt_own_event(ev)
                    if self.lineage is not None:
                        self.lineage.note_event(ev.hex(), "insert",
                                                index=ev.index)
                    self._creator_backoff.pop(cid, None)  # progress
                except ValueError as e:   # includes ForkBudgetError
                    from ..ops.forks import ParentUnknownError

                    self.insert_failures += 1
                    self.last_insert_error = str(e)
                    # only missing-ancestry failures warrant deeper
                    # resync; malformed events (bad index, foreign
                    # self-parent, fork budget) must not inflate the
                    # backoff of a creator that needs no resync
                    # (ADVICE r4 low: typed, not substring-matched)
                    if isinstance(e, ParentUnknownError) and cid is not None:
                        self._creator_backoff[cid] = min(
                            2 * max(self._creator_backoff.get(cid, 0), 1),
                            1 << 20,
                        )
                    continue
            else:
                self.insert_event(ev)
                self._wal_append(ev)
                self._adopt_own_event(ev)
                if self.lineage is not None:
                    self.lineage.note_event(ev.hex(), "insert",
                                            index=ev.index)
        self._retry_wal_orphans()
        if (other_head not in self.hg.dag.slot_of
                and (self.byzantine or other_head)):
            # the peer's head is not resolvable here — byzantine mode:
            # its parents reference events we don't hold yet; honest
            # mode: a truncated push frame (multi-frame catch-up) named
            # a head beyond what it shipped.  Keep everything inserted,
            # but the merge event cannot name it — later gossip (or the
            # next continuation frame) retries.  Returning False tells
            # the node NO self-event carried the payload, so it must
            # re-queue the pooled transactions (silently dropping them
            # here lost txs forever whenever a fleet's fork-resend
            # raced the merge head).
            self.insert_failures += 1
            self.last_insert_error = "peer head not insertable; merge skipped"
            return False
        if self.mint_blocked():
            # recovery gate: the peer's events are in, but minting here
            # could reuse a published index (WAL replay gap, or the seq
            # probe still negotiating).  Returning False tells the node
            # the payload never rode a self-event, so it requeues.
            return False
        if other_head and self._head_creator_retired(other_head):
            # membership plane: never mint a merge on a RETIRED
            # creator's head — an honest leaver stops minting at its
            # boundary, so a post-boundary head from it is spam, and a
            # merge naming it would weave that spam into honest
            # ancestry (forcing every peer to accept it forever).
            # The payload re-queues and rides the next exchange.
            self.retired_merge_skips += 1
            self.last_insert_error = "peer head creator retired; merge skipped"
            return False
        if other_head == "":
            # headless responder: an observer (a joiner waiting on its
            # epoch boundary) or a probe-blocked peer has no chain yet,
            # so there is no merge parent to name — carry the payload
            # on a self-parent event instead of minting an event with
            # an empty other-parent (which every insert path rejects)
            return self.add_self_event(payload)
        if self.head == "":
            # a freshly-admitted member's first mint (a joiner at its
            # epoch boundary): the chain needs its root before a merge
            # event can reference it
            self.init()
        ev = new_event(
            payload, (self.head, other_head), self.key.pub_bytes,
            self.seq + 1, timestamp=self.now_ns(),
        )
        self.sign_and_insert_self_event(ev)
        return True

    def _head_creator_retired(self, head_hex: str) -> bool:
        """True when ``head_hex`` is held and its creator's column is
        retired in the current epoch (the merge gate's predicate)."""
        slot = self.hg.dag.slot_of.get(head_hex)
        if slot is None:
            return False
        retired = getattr(getattr(self.hg, "cfg", None), "retired", ())
        if not retired:
            return False
        ev = self.hg.dag.events[slot]
        return self.participants.get(ev.creator) in retired

    def add_self_event(self, payload: List[bytes]) -> bool:
        """Self-parent-only event carrying pooled txs (used when there is
        nothing to sync but transactions wait; reference core.go:159-169).
        Returns False (payload not minted) while recovery blocks minting."""
        if self.mint_blocked():
            return False
        if self.head == "":
            self.init()
        ev = new_event(
            payload, (self.head, self.head), self.key.pub_bytes,
            self.seq + 1, timestamp=self.now_ns(),
        )
        self.sign_and_insert_self_event(ev)
        return True

    # ------------------------------------------------------------------

    def run_consensus(self) -> Tuple[List[Event], Dict[str, float]]:
        """DivideRounds → DecideFame → FindOrder with per-phase timings
        (reference core.go:179-202).  The fused engine dispatches per
        flush between its latency and throughput compiled surfaces
        (engine.run_consensus_timed); fork/wide engines keep the
        three-phase protocol."""
        timed = getattr(self.hg, "run_consensus_timed", None)
        if timed is not None:
            return timed()
        t0 = time.perf_counter()
        self.hg.divide_rounds()
        t1 = time.perf_counter()
        self.hg.decide_fame()
        t2 = time.perf_counter()
        new_events = self.hg.find_order()
        t3 = time.perf_counter()
        timings = {
            "divide_rounds_s": t1 - t0,
            "decide_fame_s": t2 - t1,
            "find_order_s": t3 - t2,
        }
        return new_events, timings

    # ------------------------------------------------------------------
    # stats (reference core.go:222-256)

    def consensus_events_count(self) -> int:
        return self.hg.consensus_events_count()

    def consensus_transactions_count(self) -> int:
        return self.hg.consensus_transactions

    def undetermined_events_count(self) -> int:
        return self.hg.undetermined_count

    def last_consensus_round(self) -> Optional[int]:
        return self.hg.last_consensus_round

    def last_committed_round_events_count(self) -> int:
        return self.hg.last_committed_round_events

    def stats_snapshot(self) -> Dict[str, int]:
        """Lock-free host-side counters (see engine.stats_snapshot)."""
        return self.hg.stats_snapshot()

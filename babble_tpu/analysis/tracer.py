"""JAX tracer-safety rules.

Inside a jitted function, traced values are abstract: Python ``if`` /
``while`` / ``for`` on them raises ``TracerBoolConversionError`` at
trace time in the best case and silently bakes in a constant in the
worst (when the branch condition happens to be concrete during one
trace and the function is retraced with different shapes).  Host syncs
(``.item()``, ``float(x)``, ``np.asarray(x)``) on tracers are always
errors.  These rules do a lightweight, file-local taint analysis:

- a function is *jitted* when decorated with ``jax.jit`` / ``pjit`` /
  ``partial(jax.jit, ...)``, or when the module wraps it by name in a
  ``jax.jit(fn, ...)`` call (the dominant idiom in ``ops/wide.py``);
- its parameters are traced except those named by ``static_argnums`` /
  ``static_argnames`` (and positions pre-bound through ``partial``);
- taint propagates through assignments; ``.shape`` / ``.dtype`` /
  ``.ndim`` / ``len()`` of a tracer are static and break the chain.

The analysis is file-local and heuristic by design: it cannot see
through dynamic dispatch, and it would rather miss an exotic case than
drown real kernels in noise — cross-checked by running the full rule
set over ``ops/`` in tier-1 (tests/test_static_analysis.py).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .engine import FileContext, Finding, Rule

_JIT_NAMES = {"jit", "pjit"}
# attribute reads on a tracer that yield static (host) values
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "aval", "weak_type",
                 "sharding", "_fields"}
_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_HOST_SYNC_CASTS = {"float", "int", "bool", "complex"}
_NUMPY_MODULES = {"np", "numpy", "onp"}
_UNHASHABLE = (ast.List, ast.Set, ast.Dict, ast.ListComp, ast.SetComp,
               ast.DictComp, ast.GeneratorExp)


def _is_jit_ref(node: ast.AST) -> bool:
    """Does this expression denote jax.jit / jax.pjit / bare jit?"""
    if isinstance(node, ast.Name):
        return node.id in _JIT_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr in _JIT_NAMES
    return False


def _is_partial_ref(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "partial"
    if isinstance(node, ast.Attribute):
        return node.attr == "partial"
    return False


def _literal_ints(node: ast.AST) -> Optional[Set[int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)):
                return None
            out.add(elt.value)
        return out
    return None


def _literal_strs(node: ast.AST) -> Optional[Set[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)):
                return None
            out.add(elt.value)
        return out
    return None


def _statics_from_call(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """static_argnums / static_argnames from a jit(...) or
    partial(jax.jit, ...) call's keywords."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums |= _literal_ints(kw.value) or set()
        elif kw.arg == "static_argnames":
            names |= _literal_strs(kw.value) or set()
    return nums, names


class _JitSpec:
    """How one FunctionDef is jitted: which params are non-traced."""

    def __init__(self, static_nums: Set[int], static_names: Set[str],
                 prebound: int):
        self.static_nums = static_nums
        self.static_names = static_names
        self.prebound = prebound


def _decorator_spec(fn: ast.FunctionDef) -> Optional[_JitSpec]:
    for dec in fn.decorator_list:
        if _is_jit_ref(dec):
            return _JitSpec(set(), set(), 0)
        if isinstance(dec, ast.Call):
            if _is_jit_ref(dec.func):
                nums, names = _statics_from_call(dec)
                return _JitSpec(nums, names, 0)
            if (_is_partial_ref(dec.func) and dec.args
                    and _is_jit_ref(dec.args[0])):
                nums, names = _statics_from_call(dec)
                return _JitSpec(nums, names, 0)
    return None


def _wrapped_specs(tree: ast.Module) -> Dict[str, _JitSpec]:
    """Functions jitted by name at a call site: ``jax.jit(fn, ...)``,
    ``jax.jit(partial(fn, a, b), ...)``, ``jax.jit(jax.vmap(fn))``."""
    specs: Dict[str, _JitSpec] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_jit_ref(node.func)
                and node.args):
            continue
        target = node.args[0]
        nums, names = _statics_from_call(node)
        prebound = 0
        if isinstance(target, ast.Call):
            if _is_partial_ref(target.func) and target.args:
                prebound = len(target.args) - 1
                target = target.args[0]
            elif target.args:
                # vmap/checkpoint-style wrapper: params pass through
                target = target.args[0]
        if isinstance(target, ast.Name):
            # static indices are positions of the callable jit actually
            # sees; partial pre-binding shifts them onto the inner fn
            specs[target.id] = _JitSpec(
                {i + prebound for i in nums}, names, prebound
            )
    return specs


def _iter_functions(tree: ast.Module):
    """Every FunctionDef with its enclosing-module visibility (nested
    functions are yielded too, so decorated inner defs are covered)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class _TaintScan:
    """One pass over a jitted function body.

    ``report=False`` only propagates taint through assignments (so a
    name bound late in a loop body taints earlier uses on the second
    pass); ``report=True`` emits findings."""

    def __init__(self, ctx: FileContext, fn: ast.FunctionDef,
                 spec: _JitSpec, branch_rule: "JitTracedBranchRule",
                 sync_rule: "JitHostSyncRule"):
        self.ctx = ctx
        self.fn = fn
        self.branch_rule = branch_rule
        self.sync_rule = sync_rule
        self.tainted: Set[str] = set()
        args = fn.args
        params = [a.arg for a in args.posonlyargs + args.args]
        for i, name in enumerate(params):
            if i < spec.prebound or i in spec.static_nums:
                continue
            if name in spec.static_names:
                continue
            self.tainted.add(name)
        for a in args.kwonlyargs:
            if a.arg not in spec.static_names:
                self.tainted.add(a.arg)

    # -- expression taint ------------------------------------------------

    def expr_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.expr_tainted(node.value)
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Name) and func.id == "len":
                return False  # len(tracer) is the static leading dim
            parts: List[ast.AST] = list(node.args)
            parts += [kw.value for kw in node.keywords]
            if isinstance(func, ast.Attribute):
                parts.append(func.value)
            return any(self.expr_tainted(p) for p in parts)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return False
        return any(
            self.expr_tainted(child)
            for child in ast.iter_child_nodes(node)
            if isinstance(child, ast.expr)
        )

    # -- statement walk --------------------------------------------------

    def run(self, report: bool) -> Iterator[Finding]:
        yield from self._walk(self.fn.body, report)

    def _taint_target(self, target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self.tainted.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._taint_target(elt)
        elif isinstance(target, ast.Starred):
            self._taint_target(target.value)

    def _scan_calls(self, stmt: ast.AST) -> Iterator[Finding]:
        """Host-sync findings in one statement or expression subtree
        (callers pass compound statements' own expressions only)."""
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and func.attr in _HOST_SYNC_METHODS
                    and self.expr_tainted(func.value)):
                yield self.sync_rule.finding(
                    self.ctx, node,
                    f".{func.attr}() forces a host sync on a traced "
                    f"value inside jitted `{self.fn.name}`",
                )
            elif (isinstance(func, ast.Name)
                    and func.id in _HOST_SYNC_CASTS and node.args
                    and self.expr_tainted(node.args[0])):
                yield self.sync_rule.finding(
                    self.ctx, node,
                    f"{func.id}() concretizes a traced value inside "
                    f"jitted `{self.fn.name}`",
                )
            elif (isinstance(func, ast.Attribute)
                    and func.attr in ("asarray", "array")
                    and isinstance(func.value, ast.Name)
                    and func.value.id in _NUMPY_MODULES
                    and any(self.expr_tainted(a) for a in node.args)):
                yield self.sync_rule.finding(
                    self.ctx, node,
                    f"{func.value.id}.{func.attr}() pulls a traced value "
                    f"to host inside jitted `{self.fn.name}`",
                )

    def _walk(self, body: List[ast.stmt], report: bool) -> Iterator[Finding]:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs analyzed only via their own spec
            if report:
                # scan only THIS statement's own expressions — nested
                # block bodies are scanned when the recursion reaches
                # them, so scanning the whole subtree here would emit
                # each inner finding once per nesting level
                if isinstance(stmt, (ast.If, ast.While)):
                    yield from self._scan_calls(stmt.test)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    yield from self._scan_calls(stmt.iter)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        yield from self._scan_calls(item.context_expr)
                elif not isinstance(stmt, ast.Try):
                    yield from self._scan_calls(stmt)
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                value = stmt.value
                if value is not None and self.expr_tainted(value):
                    targets = (stmt.targets
                               if isinstance(stmt, ast.Assign)
                               else [stmt.target])
                    for t in targets:
                        self._taint_target(t)
            elif isinstance(stmt, (ast.If, ast.While)):
                if report and self.expr_tainted(stmt.test):
                    kind = "if" if isinstance(stmt, ast.If) else "while"
                    yield self.branch_rule.finding(
                        self.ctx, stmt,
                        f"Python `{kind}` on a traced value inside jitted "
                        f"`{self.fn.name}` — use jnp.where/lax.cond (or "
                        "mark the argument static)",
                    )
                yield from self._walk(stmt.body, report)
                yield from self._walk(stmt.orelse, report)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                if self.expr_tainted(stmt.iter):
                    if report:
                        yield self.branch_rule.finding(
                            self.ctx, stmt,
                            f"Python `for` iterates a traced value inside "
                            f"jitted `{self.fn.name}` — use lax.scan/"
                            "fori_loop",
                        )
                    self._taint_target(stmt.target)
                yield from self._walk(stmt.body, report)
                yield from self._walk(stmt.orelse, report)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self._walk(stmt.body, report)
            elif isinstance(stmt, ast.Try):
                yield from self._walk(stmt.body, report)
                for h in stmt.handlers:
                    yield from self._walk(h.body, report)
                yield from self._walk(stmt.orelse, report)
                yield from self._walk(stmt.finalbody, report)


def _jitted_functions(ctx: FileContext):
    wrapped = _wrapped_specs(ctx.tree)
    for fn in _iter_functions(ctx.tree):
        spec = _decorator_spec(fn)
        if spec is None:
            spec = wrapped.get(fn.name)
        if spec is not None:
            yield fn, spec


def _taint_findings(ctx: FileContext) -> List[Finding]:
    """Both tracer rules' findings from ONE taint scan per file.

    The branch and sync rules share the scan (taint propagation is
    identical for both), so the result is cached on the FileContext —
    each rule's ``check`` filters by its own name instead of re-walking
    every jitted function."""
    cached = getattr(ctx, "_tracer_taint_findings", None)
    if cached is None:
        branch, sync = JitTracedBranchRule(), JitHostSyncRule()
        cached = []
        for fn, spec in _jitted_functions(ctx):
            scan = _TaintScan(ctx, fn, spec, branch, sync)
            for _ in scan.run(report=False):
                pass  # first pass: taint fixup only
            cached.extend(scan.run(report=True))
        ctx._tracer_taint_findings = cached
    return cached


class JitTracedBranchRule(Rule):
    name = "jit-traced-branch"
    description = (
        "Python if/while/for control flow on a value derived from a "
        "traced argument inside a jitted function"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for f in _taint_findings(ctx):
            if f.rule == self.name:
                yield f


class JitHostSyncRule(Rule):
    name = "jit-host-sync"
    description = (
        ".item()/.tolist()/float()/np.asarray() on a traced value "
        "inside a jitted function (forces a device sync or fails to "
        "trace)"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for f in _taint_findings(ctx):
            if f.rule == self.name:
                yield f


class JitUnhashableStaticRule(Rule):
    name = "jit-unhashable-static"
    description = (
        "static_argnums/static_argnames passed a list/set/dict literal "
        "— statics are hashed per call; use a tuple"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            is_jit = _is_jit_ref(node.func)
            is_jit_partial = (_is_partial_ref(node.func) and node.args
                              and _is_jit_ref(node.args[0]))
            if not (is_jit or is_jit_partial):
                continue
            for kw in node.keywords:
                if kw.arg in ("static_argnums", "static_argnames") and \
                        isinstance(kw.value, _UNHASHABLE):
                    yield self.finding(
                        ctx, kw.value,
                        f"{kw.arg} should be an int/str or tuple, not a "
                        f"{type(kw.value).__name__.lower()} — jit hashes "
                        "statics on every call",
                    )

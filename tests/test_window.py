"""Rolling-window / bounded-memory tests (reference caches.go semantics).

The live path must stay flat in memory forever: committed prefixes roll off
the device tensors and the host index, peers that fall behind the window
get TooLateError through the sync path, and none of it may change a single
consensus decision — the compacting engine must emit exactly the same
committed sequence as an unbounded one.
"""

import numpy as np
import pytest

from babble_tpu.common import OffsetList, TooLateError, KeyNotFoundError
from babble_tpu.consensus.engine import TpuHashgraph
from babble_tpu.sim import random_gossip_dag


def _run_chunks(engine, events, chunk):
    for i, ev in enumerate(events):
        engine.insert_event(ev.clone())
        if (i + 1) % chunk == 0:
            engine.run_consensus()
    engine.run_consensus()


def _rolled_engine(dag, **kw):
    args = dict(
        e_cap=256, s_cap=64, r_cap=32, verify_signatures=False,
        auto_compact=True, seq_window=8, compact_min=16, round_margin=2,
    )
    args.update(kw)
    return TpuHashgraph(dag.participants, **args)


# ----------------------------------------------------------------------
# OffsetList primitive


def test_offset_list_semantics():
    ol = OffsetList()
    for i in range(10):
        ol.append(i * 10)
    assert len(ol) == 10 and ol[0] == 0 and ol[-1] == 90
    assert ol[3:6] == [30, 40, 50]
    assert ol.evict_to(4) == [0, 10, 20, 30]
    assert len(ol) == 10                 # absolute indices survive eviction
    assert ol[4] == 40 and ol[-1] == 90
    with pytest.raises(TooLateError):
        ol[3]
    with pytest.raises(TooLateError):
        ol[0:6]
    with pytest.raises(KeyNotFoundError):
        ol[10]
    assert ol[4:] == [40, 50, 60, 70, 80, 90]
    assert list(ol) == [40, 50, 60, 70, 80, 90]


# ----------------------------------------------------------------------
# compaction must not change any consensus decision


@pytest.mark.parametrize("n,n_events,seed,chunk", [(4, 400, 77, 16), (5, 500, 78, 23)])
def test_compaction_matches_uncompacted(n, n_events, seed, chunk):
    dag = random_gossip_dag(n, n_events, seed=seed)
    plain = TpuHashgraph(
        dag.participants, e_cap=1024, s_cap=256, r_cap=64,
        verify_signatures=False,
    )
    rolled = _rolled_engine(dag)
    _run_chunks(plain, dag.events, chunk)
    _run_chunks(rolled, dag.events, chunk)

    assert rolled.dag.slot_base > 0, "compaction never ran"
    assert rolled._r_off > 0, "round window never rolled"
    assert plain.consensus_events() == rolled.consensus_events()
    assert plain.consensus_transactions == rolled.consensus_transactions
    assert plain.last_consensus_round == rolled.last_consensus_round
    assert plain.undetermined_count == rolled.undetermined_count


def test_window_stays_bounded():
    """The device window (live rows) must not scale with total history:
    e_cap settles and stops growing while history keeps doubling."""
    dag = random_gossip_dag(4, 1200, seed=79)
    rolled = _rolled_engine(dag)
    caps = []
    for i, ev in enumerate(dag.events):
        rolled.insert_event(ev.clone())
        if (i + 1) % 16 == 0:
            rolled.run_consensus()
            caps.append(rolled.cfg.e_cap)
    rolled.run_consensus()
    # capacity reached a fixed point long before the end of the run
    settle = caps[len(caps) // 3]
    assert caps[-1] == settle, f"e_cap kept growing: {caps}"
    live = rolled.dag.n_events - rolled.dag.slot_base
    assert live <= rolled.cfg.e_cap
    assert rolled.dag.slot_base > rolled.cfg.e_cap, (
        "evicted history should dwarf the live window"
    )
    # the host window really dropped the objects
    assert len(rolled.dag.events.window) == live


# ----------------------------------------------------------------------
# TooLate surface (reference caches.go:59-72 via the gossip diff path)


def test_evicted_window_sync_too_late():
    dag = random_gossip_dag(4, 600, seed=80)
    rolled = _rolled_engine(dag)
    _run_chunks(rolled, dag.events, 16)
    assert rolled.dag.slot_base > 0

    some_pub = next(iter(dag.participants))
    cid = dag.participants[some_pub]
    start = rolled.dag.chains[cid].start
    assert start > 0, "no chain eviction happened"
    # a peer that knows nothing (skip=0) is below the window -> too late
    with pytest.raises(TooLateError):
        rolled.dag.participant_events(some_pub, 0)
    # a peer inside the window still syncs fine
    tail = rolled.dag.participant_events(some_pub, start)
    assert len(tail) == len(rolled.dag.chains[cid]) - start

    # wire resolution of an evicted parent index is too late as well
    from babble_tpu.core.event import WireEvent

    w = WireEvent(
        transactions=[], self_parent_index=0, other_parent_creator_id=cid,
        other_parent_index=0, creator_id=(cid + 1) % 4, index=1,
        timestamp=0, r=1, s=1,
    )
    with pytest.raises(TooLateError):
        rolled.dag.read_wire_info(w)


def test_core_diff_propagates_too_late():
    """Core.diff must surface TooLateError for a stale Known vector — the
    node responds with an error instead of unbounded history (the analogue
    of the reference returning ErrTooLate from participant_events)."""
    from types import SimpleNamespace

    from babble_tpu.node.core import Core

    dag = random_gossip_dag(4, 600, seed=81)
    rolled = _rolled_engine(dag)
    _run_chunks(rolled, dag.events, 16)
    assert rolled.dag.slot_base > 0

    parts = dict(dag.participants)
    pub = next(p for p, cid in parts.items() if cid == 0)
    key = SimpleNamespace(pub_hex=pub, pub_bytes=bytes.fromhex(pub[2:]))
    core = Core(0, key, parts, engine=rolled)
    with pytest.raises(TooLateError):
        core.diff({cid: 0 for cid in range(4)})


# ----------------------------------------------------------------------
# checkpoint across a compacted window


def test_checkpoint_after_compaction(tmp_path):
    from babble_tpu.store import load_checkpoint, save_checkpoint

    dag = random_gossip_dag(4, 500, seed=82)
    rolled = _rolled_engine(dag)
    half = 400
    _run_chunks(rolled, dag.events[:half], 16)
    assert rolled.dag.slot_base > 0

    path = str(tmp_path / "ckpt")
    save_checkpoint(rolled, path)
    resumed = load_checkpoint(path)
    assert resumed.dag.slot_base == rolled.dag.slot_base
    assert resumed.consensus_events() == rolled.consensus_events()

    # both continue identically over the remaining stream
    for ev in dag.events[half:]:
        rolled.insert_event(ev.clone())
        resumed.insert_event(ev.clone())
    rolled.run_consensus()
    resumed.run_consensus()
    assert resumed.consensus_events() == rolled.consensus_events()
    assert resumed.last_consensus_round == rolled.last_consensus_round


# ----------------------------------------------------------------------
# round-window growth repair (wslot clipping recovery without re-ingest)


def test_round_repair_with_tiny_r_cap():
    """Start with r_cap too small for the stream: saturation must repair
    in place (no full re-ingest is possible once history is evicted) and
    still match an engine that had room from the start."""
    dag = random_gossip_dag(4, 400, seed=83)
    roomy = TpuHashgraph(
        dag.participants, e_cap=1024, s_cap=256, r_cap=128,
        verify_signatures=False,
    )
    tight = _rolled_engine(dag, r_cap=4, round_margin=1)
    _run_chunks(roomy, dag.events, 16)
    _run_chunks(tight, dag.events, 16)
    assert tight.cfg.r_cap > 4, "round capacity never grew"
    assert roomy.consensus_events() == tight.consensus_events()
    assert roomy.last_consensus_round == tight.last_consensus_round

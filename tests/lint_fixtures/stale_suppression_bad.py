"""Fixture: suppressions that outlived their reason — the named rule no
longer fires on the targeted line, so the waiver itself is a finding."""


def tidy(cfg):
    # the fallback was fixed to an is-None sentinel but the waiver stayed
    # babble-lint: disable=falsy-or-fallback  # MARK: stale-suppression
    v = cfg.get("size", None)
    return 256 if v is None else v


def busy(x):
    y = x + 1  # babble-lint: disable=await-state-race  # MARK: stale-suppression
    return y

"""Sharding layout + the sharded end-to-end consensus step.

Layout (annotate-and-let-XLA-partition, the pjit recipe):

- per-event vectors (sp, op, creator, seq, ts, mbit, round, witness, rr,
  cts): split along the event axis → ``P("ev")``.
- coordinate matrices la/fd ``[E+1, N]``: event rows over "ev", participant
  columns over "p" → ``P("ev", "p")``.  StronglySee's compare-count
  reduction then runs as per-shard partial counts + an ICI psum over "p"
  (inserted by XLA from the sharding constraints).
- witness tables wslot/famous ``[R+1, N]``: rounds replicated, creator
  columns over "p" → ``P(None, "p")`` (every round is touched by the fame
  scan each step; the N axis is where the width is at 10k participants).
- creator tables ce/cnt (+1-row sentinel shapes, small: ~N·S int32) and
  scalars + ingest batches: replicated.

Explicit shardings must divide the array dims, so ``pad_cfg_for_mesh``
rounds the event capacity up to a multiple of the "ev" axis (keeping the
+1 sentinel row) and pads the participant width to a multiple of "p" with
dead columns — sentinel coordinates (la=-1, fd=INT32_MAX) make padded
participants invisible to every see/vote count, and DagConfig.n_real keeps
the supermajority + coin-round thresholds on the true count.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..ops.fame import decide_fame_auto_impl
from ..ops.ingest import EventBatch, ingest_impl
from ..ops.order import decide_order_impl
from ..ops.state import DagConfig, DagState, init_state


def state_specs() -> DagState:
    """DagState-shaped pytree of PartitionSpecs."""
    ev = P("ev")
    return DagState(
        sp=ev, op=ev, creator=ev, seq=ev, ts=ev, mbit=ev,
        la=P("ev", "p"), fd=P("ev", "p"),
        round=ev, witness=ev, rr=ev, cts=ev,
        ce=P(), cnt=P(),
        wslot=P(None, "p"), famous=P(None, "p"),
        sm=P(),
        # packed witness bitplanes (kernel diet): REPLICATED.  The
        # uint8 lane axis is ceil(n/8) — 8 participant columns per
        # lane — so "p" rarely divides it (it divides n, not n/8), and
        # at [R+1, ceil(N/8)] bytes the planes are ~1/32768th of one
        # fd tensor at 10k participants: replication costs nothing and
        # keeps the lane math local to every shard
        mbr=P(), fmr=P(),
        n_events=P(), max_round=P(), lcr=P(),
        e_off=P(), s_off=P(), r_off=P(),
    )


def state_shardings(mesh: Mesh) -> DagState:
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), state_specs(),
        is_leaf=lambda x: isinstance(x, P),
    )


def batch_shardings(mesh: Mesh) -> EventBatch:
    """Ingest batches are small relative to state: replicate them."""
    rep = NamedSharding(mesh, P())
    return EventBatch(
        sp=rep, op=rep, creator=rep, seq=rep, ts=rep, mbit=rep, k=rep,
        sched=rep,
    )


def place_state(state: DagState, mesh: Mesh) -> DagState:
    return jax.device_put(state, state_shardings(mesh))


def _ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m


def pad_cfg_for_mesh(cfg: DagConfig, mesh: Mesh) -> DagConfig:
    """Round capacities up so every sharded dim divides its mesh axis."""
    ev = mesh.shape["ev"]
    p = mesh.shape["p"]
    n_pad = _ceil_to(cfg.n, p)
    e_cap = _ceil_to(cfg.e_cap + 1, ev) - 1
    n_real = cfg.n_real or cfg.n
    return DagConfig(
        n=n_pad, e_cap=e_cap, s_cap=cfg.s_cap, r_cap=cfg.r_cap,
        n_real=n_real, coord16=cfg.coord16, coord8=cfg.coord8,
        packed=cfg.packed,
    )


def consensus_step_impl(
    cfg: DagConfig, fd_mode: str, state: DagState, batch: EventBatch,
    batch_window: bool = True,
) -> DagState:
    """The full step: ingest a gossip batch, then run the whole consensus
    pipeline (DivideRounds ≡ ingest's round scan, DecideFame, FindOrder's
    device half).  This is the framework's 'training step' — the unit the
    multichip dry-run jits over a mesh.

    ``batch_window`` (static) asserts the all-window-offsets-zero
    invariant of fresh batch states, which lets wide-N fame use the
    one-hot MXU strongly-see (ops/ss.py).  A rolled-window caller (none
    exists today — the live engine drives its own phase calls with
    batch_window=False) MUST pass False here or wide-N fame miscounts."""
    state = ingest_impl(cfg, state, fd_mode, batch)
    state = decide_fame_auto_impl(cfg, state, batch_window)
    state = decide_order_impl(cfg, state)
    return state


def make_sharded_step(cfg: DagConfig, mesh: Mesh, fd_mode: str = "full"):
    """Jit the full consensus step with mesh shardings annotated in/out."""
    ss = state_shardings(mesh)
    return jax.jit(
        functools.partial(consensus_step_impl, cfg, fd_mode),
        in_shardings=(ss, batch_shardings(mesh)),
        out_shardings=ss,
        donate_argnums=(0,),
    )


def sharded_init_state(cfg: DagConfig, mesh: Mesh) -> DagState:
    return place_state(init_state(cfg), mesh)


# ----------------------------------------------------------------------
# byzantine (fork) pipeline sharding: the branch-column axis B = n*k is
# the wide dimension; partition it over "p" exactly like the honest N
# axis.  The creator-grouped reductions (strided OR over the k branch
# slots) contract B -> N, so "p" must divide n (then it divides B=k*n);
# strongly-see counts then run as per-shard partials + psum, inserted by
# XLA from the sharding constraints.


def fork_batch_specs():
    from ..ops.forks import ForkBatch

    ev = P("ev")
    return ForkBatch(
        sp=ev, op=ev, ebr=ev, eseq=ev, ecr=ev, ts=ev, mbit=ev,
        sched=P(), cp=P("p", None), ce=P("p", None), cnt=P("p"),
        owner=P("p", None), n_events=P(),
        rseed=ev, wseed=ev, s_off=P("p"),
    )


def fork_out_specs():
    from ..ops.forks import ForkOut

    ev = P("ev")
    return ForkOut(
        la=P("ev", "p"), det=P("ev", None), fd=P("ev", "p"),
        round=ev, witness=ev, wslot=P(None, "p"), famous=P(None, "p"),
        rr=ev, cts=ev, max_round=P(), lcr=P(),
    )


def pad_fork_for_mesh(cfg, batch, mesh: Mesh):
    """Round the fork batch's event axis up so e_cap+1 divides the "ev"
    mesh axis.  Padding rows replicate the sentinel (sp=-1, eseq=-1 ...),
    so they are invisible; the old sentinel row just becomes one more
    dead event row."""
    from ..ops.forks import ForkBatch

    ev = mesh.shape["ev"]
    e1_new = _ceil_to(cfg.e_cap + 1, ev)
    if e1_new == cfg.e_cap + 1:
        return cfg, batch
    pad = e1_new - (cfg.e_cap + 1)

    def pad1(a, fill):
        return jnp.concatenate(
            [a, jnp.full((pad,), fill, a.dtype)]
        )

    batch = batch._replace(
        sp=pad1(batch.sp, -1), op=pad1(batch.op, -1),
        ebr=pad1(batch.ebr, cfg.b), eseq=pad1(batch.eseq, -1),
        ecr=pad1(batch.ecr, cfg.n), ts=pad1(batch.ts, 0),
        mbit=pad1(batch.mbit, False),
        rseed=pad1(batch.rseed, -1), wseed=pad1(batch.wseed, -1),
    )
    return cfg._replace(e_cap=e1_new - 1), batch


def make_sharded_fork_step(cfg, mesh: Mesh):
    """Jit the whole fork pipeline with mesh shardings annotated."""
    from ..ops.forks import fork_pipeline_impl

    if cfg.n % mesh.shape["p"]:
        raise ValueError(
            f"mesh 'p'={mesh.shape['p']} must divide creators n={cfg.n}"
        )
    to_shard = lambda tree: jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), tree,
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.jit(
        functools.partial(fork_pipeline_impl, cfg),
        in_shardings=(to_shard(fork_batch_specs()),),
        out_shardings=to_shard(fork_out_specs()),
    )

"""Tier-1 gate for babble-lint (babble_tpu/analysis).

Two contracts, both part of every verify run:

1. the repo itself is CLEAN under the full rule set — a new finding
   (or a blanket suppression) fails the build, which is what makes the
   rule engine a regression fence rather than advice;
2. each rule family actually detects its bug class — checked against
   fixtures under tests/lint_fixtures/ that reproduce the historical
   defects (wide_engine s_cap drain-before-validate, checkpoint
   falsy-or policy fallback, jit tracer branching, gossip await races).

This module is deliberately stdlib-only (the analysis package must
import without jax/cryptography) so the gate runs even in minimal
environments.
"""

import json
import os
import subprocess
import sys

from babble_tpu.analysis import ALL_RULES, RULE_NAMES, check_file, run_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "babble_tpu")
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")


def _fixture(name):
    return os.path.join(FIXTURES, name)


def _marked_lines(path, rule):
    """1-based lines tagged ``# MARK: <rule>`` in a fixture."""
    with open(path, encoding="utf-8") as f:
        return {
            i for i, line in enumerate(f, start=1)
            if f"MARK: {rule}" in line
        }


def _found_lines(findings, rule):
    return {f.line for f in findings if f.rule == rule}


# ----------------------------------------------------------------------
# the repo gate

_TREE_FINDINGS = None


def _tree_findings():
    """One full-tree pass (suppressed included), shared by every
    project-wide assertion in this module — the pass itself is
    exercised once, the rest only read the result (the engine filters
    suppressed findings on read, so the live view is a filter)."""
    global _TREE_FINDINGS
    if _TREE_FINDINGS is None:
        _TREE_FINDINGS = run_paths([PKG], ALL_RULES,
                                   known_rules=RULE_NAMES,
                                   include_suppressed=True)
    return _TREE_FINDINGS


def test_repo_tree_is_clean():
    findings = [f for f in _tree_findings() if not f.suppressed]
    assert findings == [], "\n".join(f.format() for f in findings)


def test_rule_catalog_well_formed():
    names = [r.name for r in ALL_RULES]
    assert len(names) == len(set(names)), "duplicate rule names"
    for r in ALL_RULES:
        assert r.name and r.name == r.name.lower(), r.name
        assert " " not in r.name, f"rule name {r.name!r} is not a slug"
        assert r.description, f"rule {r.name} has no description"
    # the ISSUE-1 rule families, the ISSUE-2 blocking-call rule, the
    # ISSUE-3 chaos-reproducibility rule, the ISSUE-4 project-wide
    # flow-aware rules, the ISSUE-12 device-plane family, the
    # ISSUE-16 trust-boundary/parity families, and the ISSUE-19
    # serialization-plane family
    assert {"jit-traced-branch", "jit-host-sync", "jit-unhashable-static",
            "await-state-race", "asyncio-blocking-call",
            "drain-before-validate", "falsy-or-fallback",
            "chaos-unseeded-random", "consensus-nondeterminism",
            "held-guard-escape", "wal-before-gossip",
            "donate-use-after-free", "recompile-hazard",
            "partition-spec-coverage",
            "bytes-model-coverage",
            "unbounded-hostile-input", "engine-parity",
            "pack-unpack-parity", "checkpoint-field-coverage",
            "format-version-ratchet"} <= set(names)


def test_every_suppression_in_tree_names_a_rule():
    """No blanket disables anywhere: each suppression comment carries
    the name of a real rule.  (The engine reports violations as
    bad-suppression findings; this test states the invariant directly
    over every comment token in the package.)"""
    from babble_tpu.analysis.engine import (
        iter_python_files,
        parse_suppressions,
    )

    for path in iter_python_files([PKG]):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        _, bad, _entries = parse_suppressions(source, path, RULE_NAMES)
        assert bad == [], "\n".join(b.format() for b in bad)


# ----------------------------------------------------------------------
# rule families vs fixtures

def test_tracer_fixture_findings():
    path = _fixture("tracer_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    for rule in ("jit-traced-branch", "jit-host-sync",
                 "jit-unhashable-static"):
        assert _found_lines(findings, rule) == _marked_lines(path, rule), (
            rule, [f.format() for f in findings]
        )
    # nesting depth must not duplicate findings: exactly one finding
    # per flagged location (the MARK lines), no repeats
    locations = [(f.rule, f.line) for f in findings]
    assert len(locations) == len(set(locations)), [
        f.format() for f in findings
    ]
    # the .shape/len() branch in shape_branch_is_fine must NOT fire
    with open(path, encoding="utf-8") as f:
        clean_start = next(
            i for i, line in enumerate(f, start=1)
            if "def shape_branch_is_fine" in line
        )
    assert all(f.line < clean_start for f in findings), [
        f.format() for f in findings
    ]


def test_races_fixture_findings():
    path = _fixture("races_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(findings, "await-state-race") == _marked_lines(
        path, "await-state-race"
    ), [f.format() for f in findings]
    # the locked variant reports nothing; the block_writer (not a
    # lock) variant does
    assert len(findings) == 2


def test_blocking_fixture_findings():
    """ISSUE 2 satellite: time.sleep and blocking-socket calls inside
    async def are flagged; sync functions, non-sock receivers and
    executor-bound nested closures are not."""
    path = _fixture("asyncio_blocking_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(findings, "asyncio-blocking-call") == _marked_lines(
        path, "asyncio-blocking-call"
    ), [f.format() for f in findings]
    # nothing else fires: the clean variants stay clean
    assert len(findings) == 5, [f.format() for f in findings]


def test_codec_on_loop_fixture_findings():
    """ISSUE 6 satellite: msgpack encode/decode inside async def is
    flagged — directly, through the project call graph, and through
    the duck-typed .pack()/.unpack() name heuristic; struct.Struct
    headers, executor-bound closures and sync paths stay clean."""
    path = _fixture("codec_on_loop_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(findings, "codec-on-loop") == _marked_lines(
        path, "codec-on-loop"
    ), [f.format() for f in findings]
    assert len(findings) == 5, [f.format() for f in findings]


def test_invariants_fixture_findings():
    path = _fixture("invariants_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    for rule in ("drain-before-validate", "falsy-or-fallback"):
        assert _found_lines(findings, rule) == _marked_lines(path, rule), (
            rule, [f.format() for f in findings]
        )
    assert len(findings) == 2


def test_chaos_randomness_fixture_findings():
    """ISSUE 3 satellite: chaos code paths must carry no unseeded
    global-RNG draws — reproducibility from --seed is the whole
    contract.  The seeded idioms at the fixture's bottom stay clean."""
    path = _fixture("chaos_unseeded_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(findings, "chaos-unseeded-random") == _marked_lines(
        path, "chaos-unseeded-random"
    ), [f.format() for f in findings]
    assert len(findings) == 5, [f.format() for f in findings]


def test_chaos_randomness_rule_is_path_scoped():
    """The same source outside a chaos path is not in scope — node.py's
    heartbeat jitter is allowed its global random.random()."""
    from babble_tpu.analysis.randomness import ChaosUnseededRandomRule
    from babble_tpu.analysis.engine import FileContext

    src = "import random\n\ndef f():\n    return random.random()\n"
    rule = ChaosUnseededRandomRule()
    in_scope = list(rule.check(FileContext("pkg/chaos/injector.py", src)))
    assert len(in_scope) == 1
    out_of_scope = list(rule.check(FileContext("pkg/node/node.py", src)))
    assert out_of_scope == []


# ----------------------------------------------------------------------
# ISSUE-4 project-wide rules vs fixtures


def test_determinism_fixture_findings():
    """Taint from entropy sources into the commit path: frontier helper
    calls, unordered set iteration, env reads and global RNG all report
    in sink-reaching functions; the clean twins stay clean."""
    path = _fixture("determinism_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(findings, "consensus-nondeterminism") == (
        _marked_lines(path, "consensus-nondeterminism")
    ), [f.format() for f in findings]
    assert len(findings) == 4, [f.format() for f in findings]

    ok = check_file(_fixture("determinism_ok.py"), ALL_RULES,
                    known_rules=RULE_NAMES)
    assert ok == [], [f.format() for f in ok]


def test_determinism_cross_module_taint():
    """The tentpole property: a wall-clock helper in module A feeding
    consensus_sort in module B is visible ONLY to the project-wide pass
    — either file alone is clean."""
    a = _fixture("xmod_entropy.py")
    b = _fixture("xmod_commit.py")
    findings = run_paths([a, b], ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(findings, "consensus-nondeterminism") == (
        _marked_lines(b, "consensus-nondeterminism")
    ), [f.format() for f in findings]
    assert all(f.path == b for f in findings)
    # per-file runs cannot see the flow
    assert check_file(a, ALL_RULES, known_rules=RULE_NAMES) == []
    assert check_file(b, ALL_RULES, known_rules=RULE_NAMES) == []


def test_interprocedural_race_fixture_findings():
    """Helper-call writes count at the awaiting caller's site — the
    "extract the mutation into a method" hole is closed; lock-guarded
    helpers and disjoint attrs stay clean."""
    path = _fixture("interproc_race_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(findings, "await-state-race") == _marked_lines(
        path, "await-state-race"
    ), [f.format() for f in findings]
    assert len(findings) == 2, [f.format() for f in findings]

    ok = check_file(_fixture("interproc_race_ok.py"), ALL_RULES,
                    known_rules=RULE_NAMES)
    assert ok == [], [f.format() for f in ok]


def test_guard_fixture_findings():
    """Re-acquiring a held lock through a call chain (direct and one
    hop deep) is flagged; the already-locked-helper convention and
    distinct guards stay clean."""
    path = _fixture("guard_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(findings, "held-guard-escape") == _marked_lines(
        path, "held-guard-escape"
    ), [f.format() for f in findings]
    assert len(findings) == 2, [f.format() for f in findings]

    ok = check_file(_fixture("guard_ok.py"), ALL_RULES,
                    known_rules=RULE_NAMES)
    assert ok == [], [f.format() for f in ok]


def test_wal_gossip_fixture_findings():
    """A method that constructs-and-inserts a new self event without
    passing through wal.append in its call closure is flagged (the
    ISSUE-5 durability discipline); WAL-routed mints — direct or via a
    helper — plus free-function DAG builders and plants into ANOTHER
    node's engine stay clean."""
    path = _fixture("wal_gossip_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(findings, "wal-before-gossip") == _marked_lines(
        path, "wal-before-gossip"
    ), [f.format() for f in findings]
    assert len(findings) == 2, [f.format() for f in findings]

    ok = check_file(_fixture("wal_gossip_ok.py"), ALL_RULES,
                    known_rules=RULE_NAMES)
    assert ok == [], [f.format() for f in ok]


def test_wal_gossip_rule_passes_the_real_core():
    """node/core.py is where the rule earns its keep: every Core mint
    path (init / sync / add_self_event) routes through
    sign_and_insert_self_event -> _wal_append, and the project-wide
    pass must see that closure as clean — no suppression needed."""
    core_path = os.path.join(PKG, "node", "core.py")
    findings = _tree_findings()
    assert [f for f in findings
            if f.rule == "wal-before-gossip"
            and f.path == core_path] == []


def test_snapshot_adopt_fixture_findings():
    """A path that builds an engine from peer-supplied snapshot bytes
    without reaching the signed-state-proof helpers in its call
    closure is flagged (the ISSUE-8 verified-fast-forward discipline);
    verified adoption — direct or through a self-call helper — and
    local-disk checkpoint restores stay clean."""
    path = _fixture("snapshot_adopt_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(
        findings, "unverified-snapshot-adopt"
    ) == _marked_lines(path, "unverified-snapshot-adopt"), \
        [f.format() for f in findings]
    assert len(findings) == 3, [f.format() for f in findings]

    ok = check_file(_fixture("snapshot_adopt_ok.py"), ALL_RULES,
                    known_rules=RULE_NAMES)
    assert ok == [], [f.format() for f in ok]


def test_quorum_math_fixture_findings():
    """Inlined quorum arithmetic (2*n//3 [+1], n//3+1) is flagged
    (membership plane: thresholds must track the epoch's active set);
    helper-routed thresholds and innocent //3 capacity heuristics stay
    clean."""
    path = _fixture("quorum_math_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(
        findings, "stale-quorum-math"
    ) == _marked_lines(path, "stale-quorum-math"), \
        [f.format() for f in findings]

    ok = check_file(_fixture("quorum_math_ok.py"), ALL_RULES,
                    known_rules=RULE_NAMES)
    assert [f for f in ok if f.rule == "stale-quorum-math"] == [], \
        [f.format() for f in ok]


def test_quorum_math_clean_project_wide():
    """The whole tree routes through membership.quorum — the door the
    rule closes stays closed (zero suppressions anywhere)."""
    findings = _tree_findings()
    assert [f for f in findings if f.rule == "stale-quorum-math"] == [], \
        [f.format() for f in findings if f.rule == "stale-quorum-math"]


def test_snapshot_adopt_rule_passes_the_real_node():
    """node/node.py is where the rule earns its keep: _fast_forward
    calls load_snapshot and must reach the proof helpers through its
    closure (_verify_ff_responder / _verify_ff_quorum /
    verify_snapshot_digest) — clean with zero suppressions."""
    node_path = os.path.join(PKG, "node", "node.py")
    findings = _tree_findings()
    assert [f for f in findings
            if f.rule == "unverified-snapshot-adopt"
            and f.path == node_path] == []


def test_stale_suppression_fixture_findings():
    """A suppression whose rule no longer fires on its line is itself a
    finding, anchored at the comment; a live suppression is not."""
    path = _fixture("stale_suppression_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(findings, "stale-suppression") == _marked_lines(
        path, "stale-suppression"
    ), [f.format() for f in findings]
    assert len(findings) == 2, [f.format() for f in findings]

    ok = check_file(_fixture("stale_suppression_ok.py"), ALL_RULES,
                    known_rules=RULE_NAMES)
    assert ok == [], [f.format() for f in ok]


def test_deep_taint_chain_reports_instead_of_crashing(tmp_path):
    """Regression: the witness-chain walker used to fall off a hop
    limit and fabricate a node without a lineno, crashing the whole
    run — a deep helper chain must still yield a normal finding with a
    truncated chain in the message."""
    hops = "\n".join(
        f"def h{i}():\n    return h{i + 1}()\n" for i in range(9)
    )
    src = (
        "import time\n\n"
        f"{hops}\n"
        "def h9():\n    return time.time()\n\n"
        "def consensus_sort(events, prn):\n    return sorted(events)\n\n"
        "def commit(events):\n"
        "    t = h0()\n"
        "    return consensus_sort([(t, e) for e in events], None)\n"
    )
    path = tmp_path / "deep_chain.py"
    path.write_text(src, encoding="utf-8")
    findings = check_file(str(path), ALL_RULES, known_rules=RULE_NAMES)
    assert [f.rule for f in findings] == ["consensus-nondeterminism"]
    assert "..." in findings[0].message


def test_stale_check_respects_rule_subset():
    """Running a rule SUBSET must not misreport suppressions for
    unexecuted rules as stale — staleness is only decidable for rules
    that actually ran."""
    from babble_tpu.analysis import AwaitStateRaceRule

    path = _fixture("stale_suppression_ok.py")
    # the file's suppression names falsy-or-fallback; with only the
    # race rule running, no verdict on it is possible
    findings = check_file(path, [AwaitStateRaceRule()],
                          known_rules=RULE_NAMES)
    assert findings == [], [f.format() for f in findings]


def test_suppressed_findings_are_retained_when_asked():
    """include_suppressed keeps waived findings, flagged, so tooling
    can audit the waiver inventory."""
    path = _fixture("stale_suppression_ok.py")
    all_f = check_file(path, ALL_RULES, known_rules=RULE_NAMES,
                       include_suppressed=True)
    assert [f.rule for f in all_f] == ["falsy-or-fallback"]
    assert all_f[0].suppressed is True


def test_named_suppression_is_honored():
    findings = check_file(_fixture("suppressed_ok.py"), ALL_RULES,
                          known_rules=RULE_NAMES)
    assert findings == [], [f.format() for f in findings]


def test_blanket_suppression_is_rejected_and_ignored():
    findings = check_file(_fixture("blanket_bad.py"), ALL_RULES,
                          known_rules=RULE_NAMES)
    rules = {f.rule for f in findings}
    # the blanket disable is itself an error AND fails to silence
    assert "bad-suppression" in rules
    assert "falsy-or-fallback" in rules


# ----------------------------------------------------------------------
# CLI contract (the acceptance-criteria surface)

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "babble_tpu.analysis", *args],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )


def test_cli_exits_zero_on_clean_tree():
    proc = _run_cli("babble_tpu")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_exits_nonzero_with_locations_on_fixtures():
    proc = _run_cli(os.path.join("tests", "lint_fixtures"))
    assert proc.returncode == 1
    # findings carry file:line anchors for every family
    for rule in ("jit-traced-branch", "jit-host-sync",
                 "jit-unhashable-static", "await-state-race",
                 "asyncio-blocking-call", "drain-before-validate",
                 "falsy-or-fallback", "chaos-unseeded-random",
                 "consensus-nondeterminism", "held-guard-escape",
                 "stale-suppression", "wal-before-gossip",
                 "donate-use-after-free", "recompile-hazard",
                 "partition-spec-coverage", "bytes-model-coverage",
                 "unbounded-hostile-input", "engine-parity"):
        assert rule in proc.stdout, (rule, proc.stdout)
    import re

    assert re.search(r"lint_fixtures[/\\]\w+\.py:\d+:\d+: ", proc.stdout)


def test_cli_json_format():
    proc = _run_cli("--format=json", os.path.join("tests", "lint_fixtures"))
    assert proc.returncode == 1
    data = json.loads(proc.stdout)
    assert isinstance(data, list) and data
    assert {"rule", "path", "line", "col", "message"} <= set(data[0])


def test_cli_list_rules():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for r in ALL_RULES:
        assert r.name in proc.stdout


def test_cli_nonexistent_path_is_a_usage_error():
    # exit 0 must mean "checked and clean", never "checked nothing":
    # a typo'd CI path has to fail loudly
    proc = _run_cli("no_such_dir_xyz")
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "no_such_dir_xyz" in proc.stderr


def test_cli_rule_subset_keeps_suppression_vocabulary():
    # running a single rule must not misreport suppressions that name
    # other (real) rules as unknown (nor report them stale: staleness
    # is only decidable for rules that ran)
    proc = _run_cli("--rules=falsy-or-fallback", "babble_tpu")
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ----------------------------------------------------------------------
# machine-readable output (--json) + incremental cache (--cache)


def test_cli_jsonl_schema_roundtrips():
    """--json emits one finding per line; every line carries the full
    schema (rule/path/line/col/message/suppressed) and survives a
    Finding round-trip.  Suppressed findings ARE in the stream, flagged
    — the live set must equal the in-process run exactly."""
    from babble_tpu.analysis import Finding

    proc = _run_cli("--json", FIXTURES)
    assert proc.returncode == 1
    rows = [json.loads(line) for line in proc.stdout.splitlines() if line]
    assert rows, proc.stdout
    for row in rows:
        assert set(row) == {"rule", "path", "line", "col", "message",
                            "suppressed"}, row
        f = Finding.from_dict(row)
        assert f.to_dict() == row
    # stale_suppression_ok.py's waived falsy-or-fallback rides along,
    # flagged — that is the point of the field
    assert any(r["suppressed"] for r in rows), proc.stdout
    live = {(r["path"], r["line"], r["rule"]) for r in rows
            if not r["suppressed"]}
    expected = {
        (f.path, f.line, f.rule)
        for f in run_paths([FIXTURES], ALL_RULES, known_rules=RULE_NAMES)
    }
    assert live == expected


def test_cache_hit_skips_analysis_and_edit_invalidates(tmp_path):
    """The whole-run cache: an untouched tree replays findings without
    re-running anything; touching one file (mtime) or editing it (new
    finding) forces a full recompute."""
    import shutil
    from unittest import mock

    from babble_tpu.analysis import cache as cache_mod
    from babble_tpu.analysis import run_paths_cached

    src = tmp_path / "src"
    src.mkdir()
    for name in ("determinism_bad.py", "guard_ok.py"):
        shutil.copy(_fixture(name), src / name)
    cache_file = str(tmp_path / ".babble_lint_cache")

    cold, hit = run_paths_cached([str(src)], ALL_RULES, cache_file,
                                 known_rules=RULE_NAMES)
    assert hit is False and len(cold) == 4

    # a hit must not parse or analyze ANYTHING: the real run_paths is
    # unreachable on the hit path
    with mock.patch.object(cache_mod, "run_paths",
                           side_effect=AssertionError("cache missed")):
        warm, hit = run_paths_cached([str(src)], ALL_RULES, cache_file,
                                     known_rules=RULE_NAMES)
    assert hit is True
    assert warm == cold

    # a --json run (include_suppressed=True) shares the same entry:
    # the store is suppressed-inclusive, the view is filtered on read
    with mock.patch.object(cache_mod, "run_paths",
                           side_effect=AssertionError("cache missed")):
        full, hit = run_paths_cached([str(src)], ALL_RULES, cache_file,
                                     known_rules=RULE_NAMES,
                                     include_suppressed=True)
    assert hit is True
    assert [f for f in full if not f.suppressed] == cold

    # mtime bump alone invalidates (content unread by the key)
    os.utime(src / "guard_ok.py", ns=(1, 1))
    again, hit = run_paths_cached([str(src)], ALL_RULES, cache_file,
                                  known_rules=RULE_NAMES)
    assert hit is False and again == cold

    # a real edit changes the result through the refreshed cache
    with open(src / "guard_ok.py", "a", encoding="utf-8") as f:
        f.write("\n\ndef bad(cfg):\n    return cfg.get('k', 5) or 5\n")
    edited, hit = run_paths_cached([str(src)], ALL_RULES, cache_file,
                                   known_rules=RULE_NAMES)
    assert hit is False
    assert "falsy-or-fallback" in {f.rule for f in edited}


def test_cached_run_is_fast_enough(tmp_path):
    """Acceptance criterion: the cached project-wide pass costs <= 25%
    of the cold pass (in practice it is a stat sweep, ~100x cheaper).
    The rule set here is ALL_RULES, so every family added since —
    including the ISSUE-12 device plane, whose jit registry and
    donate-through fixpoint walk the whole call graph — rides the same
    budget: new cross-module analyses may grow the COLD pass but can
    never regress the cached one, which is what tier-1 pays per verify
    run."""
    import time

    from babble_tpu.analysis import run_paths_cached

    cache_file = str(tmp_path / ".babble_lint_cache")
    t0 = time.perf_counter()
    cold, hit = run_paths_cached([PKG], ALL_RULES, cache_file,
                                 known_rules=RULE_NAMES)
    t_cold = time.perf_counter() - t0
    assert hit is False
    # best-of-3 warm pass: the real ratio is ~5%, so 25% leaves a wide
    # margin, but a single stat sweep can still land on a scheduler
    # stall under CI contention — take the minimum to measure the
    # mechanism, not the noise
    t_warm = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        warm, hit = run_paths_cached([PKG], ALL_RULES, cache_file,
                                     known_rules=RULE_NAMES)
        t_warm = min(t_warm, time.perf_counter() - t0)
        assert hit is True and warm == cold
    assert t_warm <= 0.25 * t_cold, (t_warm, t_cold)


def test_cli_cache_flag(tmp_path):
    cache_file = str(tmp_path / "lint.cache")
    p1 = _run_cli("--cache", cache_file, "babble_tpu")
    assert p1.returncode == 0, p1.stdout + p1.stderr
    assert os.path.exists(cache_file)
    p2 = _run_cli("--cache", cache_file, "babble_tpu")
    assert p2.returncode == 0, p2.stdout + p2.stderr


def test_corrupt_cache_is_a_miss_not_a_crash(tmp_path):
    from babble_tpu.analysis import run_paths_cached

    cache_file = tmp_path / "lint.cache"
    cache_file.write_text("{not json", encoding="utf-8")
    findings, hit = run_paths_cached(
        [_fixture("guard_bad.py")], ALL_RULES, str(cache_file),
        known_rules=RULE_NAMES)
    assert hit is False
    assert {f.rule for f in findings} == {"held-guard-escape"}


def test_cli_lint_verb():
    """`babble-tpu lint ...` forwards to the analysis CLI (same exit
    codes, same --json stream) so CI has one entrypoint."""
    proc = subprocess.run(
        [sys.executable, "-m", "babble_tpu.cli", "lint", "--json",
         os.path.join("tests", "lint_fixtures", "guard_bad.py")],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    rows = [json.loads(line) for line in proc.stdout.splitlines() if line]
    assert {r["rule"] for r in rows} == {"held-guard-escape"}
    clean = subprocess.run(
        [sys.executable, "-m", "babble_tpu.cli", "lint", "babble_tpu"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr

# ----------------------------------------------------------------------
# ISSUE-16: trust-boundary taint + engine parity + suppression ratchet


def test_engine_parity_fixture_findings():
    """Two ported engine surfaces sharing one file: the one whose
    insert closure reaches clamp_eff_ts is clean, the drifted twin is
    flagged at its insert_event def; integration (retired gate, WAL)
    and adoption (meta bounds) invariants are witnessed on the
    surrounding Runtime/load_snapshot, so exactly one finding."""
    path = _fixture("engine_parity_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(findings, "engine-parity") == _marked_lines(
        path, "engine-parity"
    ), [f.format() for f in findings]
    assert len(findings) == 1, [f.format() for f in findings]

    ok = check_file(_fixture("engine_parity_ok.py"), ALL_RULES,
                    known_rules=RULE_NAMES)
    assert ok == [], [f.format() for f in ok]


def test_hostile_input_fixture_findings():
    """Peer-decoded sizes reaching allocation shapes, repeat counts,
    loop bounds and bytearray extents unguarded are flagged (including
    through a helper-return hop); the guarded twins — check_*-family
    call, min() clamp, raise-guarded if, len() of the frame — stay
    clean."""
    path = _fixture("hostile_input_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(findings, "unbounded-hostile-input") == (
        _marked_lines(path, "unbounded-hostile-input")
    ), [f.format() for f in findings]
    assert len(findings) == 4, [f.format() for f in findings]

    ok = check_file(_fixture("hostile_input_ok.py"), ALL_RULES,
                    known_rules=RULE_NAMES)
    assert ok == [], [f.format() for f in ok]


def test_hostile_input_cross_module_taint():
    """The tentpole property for the taint family: an unpack in module
    A feeding an allocation shape in module B is visible ONLY to the
    project-wide pass — either file alone is clean — and the witness
    chain in the message names the wire-side source."""
    a = _fixture("xmod_wire.py")
    b = _fixture("xmod_alloc.py")
    findings = run_paths([a, b], ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(findings, "unbounded-hostile-input") == (
        _marked_lines(b, "unbounded-hostile-input")
    ), [f.format() for f in findings]
    assert all(f.path == b for f in findings)
    assert any("unpackb" in f.message for f in findings), [
        f.message for f in findings
    ]
    # per-file runs cannot see the flow
    assert check_file(a, ALL_RULES, known_rules=RULE_NAMES) == []
    assert check_file(b, ALL_RULES, known_rules=RULE_NAMES) == []


def test_new_families_clean_and_baseline_matches_tree():
    """Both new families pass the real tree with ZERO suppressions (the
    fork-engine clamp gap they surfaced is fixed in code, not waived),
    and the committed ratchet baseline is exactly the tree's current
    waiver inventory — neither stale entries nor unrecorded waivers."""
    findings = _tree_findings()
    new = [f for f in findings
           if f.rule in ("unbounded-hostile-input", "engine-parity")]
    assert new == [], [f.format() for f in new]

    counts = {}
    for f in findings:
        if f.suppressed:
            rel = os.path.relpath(f.path, REPO).replace(os.sep, "/")
            key = f"{rel}::{f.rule}"
            counts[key] = counts.get(key, 0) + 1
    with open(os.path.join(REPO, ".babble-lint-baseline.json"),
              encoding="utf-8") as fh:
        assert json.load(fh)["waived"] == counts


def test_cli_baseline_ratchet(tmp_path):
    """--baseline end to end on a throwaway tree: a missing baseline
    is a loud usage error (never a silently-off ratchet), --write
    records the waiver inventory, pre-existing waivers pass, and a NEW
    suppression in a known pair fails with a diff on stderr."""
    import shutil

    tree = tmp_path / "tree"
    tree.mkdir()
    shutil.copy(_fixture("stale_suppression_ok.py"), tree / "waived.py")
    baseline = tmp_path / "baseline.json"

    miss = _run_cli("--baseline", str(baseline), str(tree))
    assert miss.returncode == 2, miss.stdout + miss.stderr
    assert "cannot read baseline" in miss.stderr

    wrote = _run_cli("--baseline", str(baseline), "--write-baseline",
                     str(tree))
    assert wrote.returncode == 0, wrote.stdout + wrote.stderr
    key = (str(tree / "waived.py").replace(os.sep, "/")
           + "::falsy-or-fallback")
    doc = json.loads(baseline.read_text(encoding="utf-8"))
    assert doc == {"version": 1, "waived": {key: 1}}

    ok = _run_cli("--baseline", str(baseline), str(tree))
    assert ok.returncode == 0, ok.stdout + ok.stderr

    # one more waiver in the same path::rule pair exceeds the count
    with open(tree / "waived.py", "a", encoding="utf-8") as f:
        f.write(
            "\n\ndef more(cfg):\n"
            "    return cfg.get('batch', 8) or 8"
            "  # babble-lint: disable=falsy-or-fallback\n"
        )
    broken = _run_cli("--baseline", str(baseline), str(tree))
    assert broken.returncode == 1, broken.stdout + broken.stderr
    assert "NEW suppression" in broken.stderr
    assert "falsy-or-fallback" in broken.stderr


def test_cli_sarif_carries_new_rules():
    """--sarif advertises both ISSUE-16 rules in the driver catalog and
    carries their fixture findings as results."""
    proc = _run_cli("--sarif", FIXTURES)
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    run = doc["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    result_ids = {r["ruleId"] for r in run["results"]}
    for rule in ("unbounded-hostile-input", "engine-parity"):
        assert rule in rule_ids, sorted(rule_ids)
        assert rule in result_ids, sorted(result_ids)

# ----------------------------------------------------------------------
# ISSUE-19: serialization-plane schema lint


def test_serial_parity_fixture_findings():
    """Every drift direction of a pack/unpack pair: a packed field the
    reader never binds, a read past the packed arity, an unguarded
    tail read above a guarded position, a dict key that vanishes on
    read and one the writer never produces; the clean twin's guarded
    tails, .get defaults and **-absorbing constructor stay clean."""
    path = _fixture("serial_parity_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(findings, "pack-unpack-parity") == _marked_lines(
        path, "pack-unpack-parity"
    ), [f.format() for f in findings]
    assert len(findings) == 5, [f.format() for f in findings]

    ok = check_file(_fixture("serial_parity_ok.py"), ALL_RULES,
                    known_rules=RULE_NAMES)
    assert ok == [], [f.format() for f in ok]


def test_serial_coverage_fixture_findings():
    """The exact-partition contract on builder/checker/restore trios:
    a key the checker never bounds, a key no restore path reads, and a
    checker demanding a key no builder writes all fire; the twin whose
    every key is bounded and restored (with a .get backfill for the
    versioned tail key) stays clean."""
    path = _fixture("serial_coverage_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(findings, "checkpoint-field-coverage") == (
        _marked_lines(path, "checkpoint-field-coverage")
    ), [f.format() for f in findings]
    assert len(findings) == 3, [f.format() for f in findings]

    ok = check_file(_fixture("serial_coverage_ok.py"), ALL_RULES,
                    known_rules=RULE_NAMES)
    assert ok == [], [f.format() for f in ok]


def test_serial_ratchet_fixture_findings():
    """The fixtures' committed manifest deliberately records stale
    inventories for serial_ratchet_bad: a pair that grew a field, a
    builder that grew one under an unbumped constant (the bump-demand
    flavor names the constant), and a surface never recorded at all;
    the accurately-recorded twin stays clean."""
    path = _fixture("serial_ratchet_bad.py")
    findings = check_file(path, ALL_RULES, known_rules=RULE_NAMES)
    assert _found_lines(findings, "format-version-ratchet") == (
        _marked_lines(path, "format-version-ratchet")
    ), [f.format() for f in findings]
    assert len(findings) == 3, [f.format() for f in findings]
    messages = " | ".join(f.message for f in findings)
    assert "without bumping `ROT_FORMAT_VERSION`" in messages
    assert "not recorded in the format manifest" in messages

    ok = check_file(_fixture("serial_ratchet_ok.py"), ALL_RULES,
                    known_rules=RULE_NAMES)
    assert ok == [], [f.format() for f in ok]


def test_format_manifest_committed_and_matches_tree():
    """The repo-root .babble-format-manifest.json is the reviewable
    record of every serialized surface: it must exist and equal the
    inventory recomputed from the tree byte for byte — a drifted or
    hand-edited manifest fails tier-1 even where the ratchet rule
    itself would stay quiet (e.g. a whole module deleted)."""
    from babble_tpu.analysis.serial import (
        MANIFEST_NAME, compute_surfaces, load_manifest, manifest_entry,
    )

    mpath = os.path.join(REPO, MANIFEST_NAME)
    assert os.path.isfile(mpath), "format manifest is not committed"
    recorded, err = load_manifest(mpath)
    assert err is None, err
    computed = {
        name: manifest_entry(s, REPO)
        for name, s in compute_surfaces([PKG]).items()
    }
    assert recorded == computed
    # the surfaces the ISSUE names are actually under the ratchet
    for name in ("wire:babble_tpu.net.commands:FastForwardResponse",
                 "meta:babble_tpu.store.checkpoint:_build_meta",
                 "meta:babble_tpu.store.checkpoint:_build_fork_meta",
                 "frame:babble_tpu.wal.log:_HDR",
                 "manifest:babble_tpu.ops.aot:ENGINE_CACHE_VERSION"):
        assert name in recorded, sorted(recorded)


def test_serial_families_clean_on_tree_with_zero_suppressions():
    """All three new families pass the real tree with ZERO waivers:
    the live coverage gaps they surfaced (unbounded consensus/received
    payloads in both checkers, the unbounded anchors ring) are fixed
    in checkpoint.py, not suppressed."""
    new = [f for f in _tree_findings()
           if f.rule in ("pack-unpack-parity", "checkpoint-field-coverage",
                         "format-version-ratchet")]
    assert new == [], [f.format() for f in new]


def test_meta_field_add_demo(tmp_path):
    """The acceptance demo, end to end in a throwaway tree: adding a
    checkpoint meta field fails lint at the coverage AND ratchet
    families, --write-format-manifest REFUSES while the version
    constant is unbumped, and only bounds + restore backfill + bump +
    re-record bring the tree back to clean."""
    import shutil

    tree = tmp_path / "tree"
    tree.mkdir()
    shutil.copy(_fixture("serial_coverage_ok.py"), tree / "ckpt.py")
    manifest = tree / ".babble-format-manifest.json"
    manifest.write_text('{"version": 1, "surfaces": {}}\n',
                        encoding="utf-8")
    wrote = _run_cli("--write-format-manifest", str(tree))
    assert wrote.returncode == 0, wrote.stdout + wrote.stderr
    assert str(manifest) in wrote.stderr
    assert _run_cli(str(tree)).returncode == 0

    src = (tree / "ckpt.py").read_text(encoding="utf-8")
    src = src.replace('"carry": engine.carry,',
                      '"carry": engine.carry,\n'
                      '        "horizon": engine.horizon,')
    (tree / "ckpt.py").write_text(src, encoding="utf-8")

    broken = _run_cli(str(tree))
    assert broken.returncode == 1, broken.stdout + broken.stderr
    assert "checkpoint-field-coverage" in broken.stdout
    assert "format-version-ratchet" in broken.stdout
    assert "horizon" in broken.stdout

    # the sanctioned bump path refuses while the constant is unbumped
    refused = _run_cli("--write-format-manifest", str(tree))
    assert refused.returncode == 2, refused.stdout + refused.stderr
    assert "unbumped version constant" in refused.stderr
    assert _run_cli(str(tree)).returncode == 1  # nothing was recorded

    # bounds + restore backfill + version bump...
    src = src.replace("FORMAT_VERSION = 4", "FORMAT_VERSION = 5")
    src = src.replace(
        '    anchors = meta.get("anchors", [])',
        '    horizon = meta.get("horizon", 0)\n'
        '    if not isinstance(horizon, int) or horizon < 0:\n'
        '        raise ValueError("bad horizon")\n'
        '    anchors = meta.get("anchors", [])',
    )
    src += '\n\ndef restore_horizon(engine, meta):\n' \
           '    engine.horizon = int(meta.get("horizon", 0))\n'
    (tree / "ckpt.py").write_text(src, encoding="utf-8")

    # ...still fails until the manifest records the new inventory
    stale = _run_cli(str(tree))
    assert stale.returncode == 1, stale.stdout + stale.stderr
    assert "format-version-ratchet" in stale.stdout
    assert "checkpoint-field-coverage" not in stale.stdout

    rerec = _run_cli("--write-format-manifest", str(tree))
    assert rerec.returncode == 0, rerec.stdout + rerec.stderr
    assert _run_cli(str(tree)).returncode == 0


def test_msgpack_reorder_demo(tmp_path):
    """Reordering positional msgpack fields keeps pack/unpack parity
    happy (every position still reads) but the ratchet catches the
    silent wire break: the recorded inventory is order-sensitive."""
    import shutil

    tree = tmp_path / "tree"
    tree.mkdir()
    shutil.copy(_fixture("serial_parity_ok.py"), tree / "wire.py")
    (tree / ".babble-format-manifest.json").write_text(
        '{"version": 1, "surfaces": {}}\n', encoding="utf-8")
    assert _run_cli("--write-format-manifest", str(tree)).returncode == 0
    assert _run_cli(str(tree)).returncode == 0

    src = (tree / "wire.py").read_text(encoding="utf-8")
    block = ("            self.from_addr,\n"
             "            self.seq,\n"
             "            self.sig_r,\n"
             "            self.sig_s,\n")
    assert block in src
    src = src.replace(block,
                      "            self.seq,\n"
                      "            self.from_addr,\n"
                      "            self.sig_r,\n"
                      "            self.sig_s,\n")
    (tree / "wire.py").write_text(src, encoding="utf-8")

    proc = _run_cli(str(tree))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "format-version-ratchet" in proc.stdout
    assert "reordered" in proc.stdout
    assert "pack-unpack-parity" not in proc.stdout


def test_manifest_edit_invalidates_cache(tmp_path):
    """The whole-run cache must key on every manifest that could
    shadow a linted file: editing the manifest alone (no source file
    touched) is a miss and the ratchet re-fires."""
    import shutil

    from babble_tpu.analysis import run_paths_cached
    from babble_tpu.analysis.serial import compute_surfaces, write_manifest

    tree = tmp_path / "tree"
    tree.mkdir()
    shutil.copy(_fixture("serial_ratchet_ok.py"), tree / "wire.py")
    manifest = tree / ".babble-format-manifest.json"
    manifest.write_text('{"version": 1, "surfaces": {}}\n',
                        encoding="utf-8")
    assert write_manifest(str(manifest),
                          compute_surfaces([str(tree)])) == []
    cache_file = str(tmp_path / "lint.cache")

    cold, hit = run_paths_cached([str(tree)], ALL_RULES, cache_file,
                                 known_rules=RULE_NAMES)
    assert hit is False and cold == []
    warm, hit = run_paths_cached([str(tree)], ALL_RULES, cache_file,
                                 known_rules=RULE_NAMES)
    assert hit is True and warm == []

    doc = json.loads(manifest.read_text(encoding="utf-8"))
    doc["surfaces"]["wire:wire:RecordedMsg"]["fields"] = ["from_addr"]
    manifest.write_text(json.dumps(doc), encoding="utf-8")
    edited, hit = run_paths_cached([str(tree)], ALL_RULES, cache_file,
                                   known_rules=RULE_NAMES)
    assert hit is False
    assert {f.rule for f in edited} == {"format-version-ratchet"}, [
        f.format() for f in edited
    ]


def test_cli_changed_scopes_reporting(tmp_path):
    """--changed on a throwaway git repo: a finding in a committed,
    untouched file is filtered out of the report while the same run
    without --changed still fails; a new (untracked) file with a
    finding brings the flag back to exit 1; outside git it is a loud
    usage error, never a silently-empty report."""
    import shutil

    repo = tmp_path / "wt"
    repo.mkdir()
    shutil.copy(_fixture("guard_bad.py"), repo / "old.py")

    def run(*args, cwd):
        env = dict(os.environ, PYTHONPATH=REPO)
        return subprocess.run(
            [sys.executable, "-m", "babble_tpu.analysis", *args],
            cwd=str(cwd), capture_output=True, text=True, timeout=120,
            env=env,
        )

    def git(*args):
        subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
            cwd=str(repo), capture_output=True, text=True, check=True,
        )

    # outside a git checkout the flag is a usage error
    assert run("--changed", ".", cwd=repo).returncode == 2

    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")

    full = run(".", cwd=repo)
    assert full.returncode == 1, full.stdout + full.stderr
    scoped = run("--changed", ".", cwd=repo)
    assert scoped.returncode == 0, scoped.stdout + scoped.stderr

    shutil.copy(_fixture("invariants_bad.py"), repo / "new.py")
    touched = run("--changed", ".", cwd=repo)
    assert touched.returncode == 1, touched.stdout + touched.stderr
    assert "new.py" in touched.stdout
    assert "old.py" not in touched.stdout


def test_cli_streams_carry_serial_rules():
    """--json and --sarif both carry the three new families end to
    end: catalog entries in the SARIF driver, findings in both
    streams."""
    serial_rules = {"pack-unpack-parity", "checkpoint-field-coverage",
                    "format-version-ratchet"}
    proc = _run_cli("--sarif", FIXTURES)
    assert proc.returncode == 1
    run = json.loads(proc.stdout)["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    result_ids = {r["ruleId"] for r in run["results"]}
    assert serial_rules <= rule_ids, sorted(rule_ids)
    assert serial_rules <= result_ids, sorted(result_ids)

    rows = []
    for name in ("serial_parity_bad.py", "serial_coverage_bad.py",
                 "serial_ratchet_bad.py"):
        jp = _run_cli("--json", _fixture(name))
        assert jp.returncode == 1
        rows += [json.loads(line) for line in jp.stdout.splitlines()
                 if line]
    assert {r["rule"] for r in rows} == serial_rules

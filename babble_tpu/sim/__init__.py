"""Synthetic DAG generation and batch consensus simulation.

The north-star benchmark path (BASELINE.json): generate realistic gossip
DAGs at scale (uniform arrival; byzantine-fork variants planned), push them
through the TPU engine in batch, and measure events/sec to consensus order.
"""

from .generator import GeneratedDag, random_gossip_dag

__all__ = ["GeneratedDag", "random_gossip_dag"]

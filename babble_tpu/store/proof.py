"""Signed state proofs for fast-forward bootstrap (ISSUE 8 tentpole b).

Snapshot trust used to be the babbleio fast-sync assumption: the joiner
re-verifies every event SIGNATURE in the window, but the consensus
decisions (rounds, fame, committed order) ride on trust in the single
serving peer — the protocol-aware-recovery failure mode (Alagappan et
al., FAST'18): one byzantine bootstrap peer can feed a forged state
that the joiner silently installs.

The proof scheme closes that to the honest-quorum assumption consensus
already makes:

- every engine maintains a rolling **commit digest** — a hash chain
  over the committed order, identical across honest nodes at every
  position (consensus/digest.py);
- a fast-forward responder signs ``(snapshot_hash, lcr, position,
  digest)`` with its participant key (``sign_snapshot_proof``) — the
  proof binds the exact bytes served to a specific committed frontier;
- any peer can attest ``(position, digest)`` from its own chain
  (``sign_attestation``), and the joiner requires ``n//3 + 1`` matching
  attestations (responder included) before adopting — at most ``f <
  n/3`` byzantine signers means any f+1 matching set contains an honest
  node, so a rewritten history can never gather a quorum;
- the joiner additionally re-folds the snapshot's consensus window over
  its digest anchor (``verify_snapshot_digest``): a forger that keeps
  the honest digest while permuting the window is caught locally,
  before any network round-trip.

A rejected snapshot is refused LOUDLY (``babble_ff_proof_rejects_total``)
and the joiner falls back to another peer on its next gossip round.
"""

from __future__ import annotations

import struct
from typing import Optional

from ..crypto import keys as crypto_keys
from ..crypto.keys import KeyPair, sha256

#: v2 (membership plane): the consensus epoch is bound into both proof
#: messages — a snapshot claiming one epoch's peer set under another
#: epoch's digest, or an attestation replayed across an epoch
#: boundary, fails signature verification outright
_SNAPSHOT_TAG = b"babble-ff-snapshot:v2"
_ATTEST_TAG = b"babble-ff-attest:v2"


def snapshot_hash(snapshot: bytes) -> bytes:
    return sha256(snapshot)


def _snapshot_msg(snap_hash: bytes, lcr: int, position: int,
                  digest: str, epoch: int) -> bytes:
    return sha256(
        _SNAPSHOT_TAG + snap_hash
        + struct.pack(">qQQ", lcr, position, epoch)
        + digest.encode("ascii")
    )


def _attest_msg(position: int, digest: str, epoch: int) -> bytes:
    return sha256(
        _ATTEST_TAG + struct.pack(">QQ", position, epoch)
        + digest.encode("ascii")
    )


def sign_snapshot_proof(key: KeyPair, snap_hash: bytes, lcr: int,
                        position: int, digest: str, epoch: int = 0):
    """Responder side: sign the (snapshot, frontier, epoch) binding."""
    return key.sign_digest(
        _snapshot_msg(snap_hash, lcr, position, digest, epoch)
    )


def verify_snapshot_proof(pub_hex: str, snap_hash: bytes, lcr: int,
                          position: int, digest: str,
                          r: int, s: int, epoch: int = 0) -> bool:
    try:
        pub = crypto_keys.from_pub_bytes(
            crypto_keys.pub_hex_to_bytes(pub_hex)
        )
        return crypto_keys.verify(
            pub, _snapshot_msg(snap_hash, lcr, position, digest, epoch),
            r, s
        )
    except Exception:
        return False


def sign_attestation(key: KeyPair, position: int, digest: str,
                     epoch: int = 0):
    """Attester side: co-sign a committed frontier you hold yourself."""
    return key.sign_digest(_attest_msg(position, digest, epoch))


def verify_attestation(pub_hex: str, position: int, digest: str,
                       r: int, s: int, epoch: int = 0) -> bool:
    try:
        pub = crypto_keys.from_pub_bytes(
            crypto_keys.pub_hex_to_bytes(pub_hex)
        )
        return crypto_keys.verify(
            pub, _attest_msg(position, digest, epoch), r, s
        )
    except Exception:
        return False


def verify_snapshot_digest(engine, digest: str,
                           position: int) -> Optional[str]:
    """Local half of snapshot verification: the restored engine's
    commit-digest state must be internally consistent AND match the
    signed proof.  Returns an error string (reject the snapshot) or
    None.  Runs before any attestation round-trip — a forgery that is
    cheap to detect must be cheap to reject."""
    from ..consensus.digest import fold

    dg = getattr(engine, "_digest", None)
    if dg is None:
        return "snapshot engine carries no commit digest"
    if dg.length != position or dg.head != digest:
        return (
            f"snapshot digest frontier ({dg.length}, {dg.head[:12]}…) "
            f"does not match the signed proof ({position}, {digest[:12]}…)"
        )
    window = list(engine.consensus)
    start = getattr(engine.consensus, "start", 0)
    if start + len(window) != dg.length:
        return (
            f"snapshot consensus window ({start}+{len(window)} entries) "
            f"inconsistent with digest length {dg.length}"
        )
    if dg.anchor is None or dg.anchor_pos != start:
        # An un-anchorable window would skip the re-fold — which is
        # exactly the dodge a forger wants (keep the honest head, set
        # anchor=None, permute the window; the quorum then co-signs a
        # head that no longer covers what the joiner adopts).  Honest
        # responders essentially never land here: evict_to only loses
        # its anchor when the trimmed window outruns RECENT_POSITIONS
        # (consensus_window > 8192).  Reject; the joiner retries
        # another peer.
        return (
            "snapshot digest does not anchor its consensus window "
            f"(anchor_pos {dg.anchor_pos} vs window start {start}) — "
            "the committed window cannot be verified against the "
            "signed digest"
        )
    if fold(dg.anchor, window) != dg.head:
        return (
            "snapshot consensus window does not re-fold to the signed "
            "digest — committed history was rewritten"
        )
    return None

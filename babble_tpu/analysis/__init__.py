"""babble-lint: repo-native static analysis (stdlib-only, tier-1).

Rule families (see ISSUE 1 / the rules' module docstrings):

- :mod:`.tracer` — JAX tracer safety inside jitted functions
- :mod:`.races` — asyncio interleaving races across ``await``
- :mod:`.blocking` — blocking calls (time.sleep, socket I/O) in coroutines
- :mod:`.invariants` — drain-before-validate + falsy-config fallback

Run as ``python -m babble_tpu.analysis [--format=text|json] [paths]``;
suppress a finding with ``# babble-lint: disable=<rule-name>`` on the
flagged line (or the line above).  The full rule set runs over
``babble_tpu/`` in tier-1 (tests/test_static_analysis.py), so a new
finding — or a blanket suppression — fails the build.

Adding a rule: subclass :class:`~.engine.Rule`, implement
``check(ctx)``, append an instance to :data:`ALL_RULES`.  Keep rules
stdlib-only — this package must import in environments without jax.
"""

from .engine import (
    BAD_SUPPRESSION,
    PARSE_ERROR,
    FileContext,
    Finding,
    Rule,
    check_file,
    run_paths,
)
from .blocking import AsyncioBlockingCallRule
from .invariants import DrainBeforeValidateRule, FalsyOrFallbackRule
from .races import AwaitStateRaceRule
from .randomness import ChaosUnseededRandomRule
from .tracer import (
    JitHostSyncRule,
    JitTracedBranchRule,
    JitUnhashableStaticRule,
)

ALL_RULES = [
    JitTracedBranchRule(),
    JitHostSyncRule(),
    JitUnhashableStaticRule(),
    AwaitStateRaceRule(),
    AsyncioBlockingCallRule(),
    ChaosUnseededRandomRule(),
    DrainBeforeValidateRule(),
    FalsyOrFallbackRule(),
]

RULE_NAMES = {r.name for r in ALL_RULES} | {BAD_SUPPRESSION, PARSE_ERROR}

__all__ = [
    "ALL_RULES",
    "RULE_NAMES",
    "BAD_SUPPRESSION",
    "PARSE_ERROR",
    "FileContext",
    "Finding",
    "Rule",
    "check_file",
    "run_paths",
    "AsyncioBlockingCallRule",
    "AwaitStateRaceRule",
    "ChaosUnseededRandomRule",
    "DrainBeforeValidateRule",
    "FalsyOrFallbackRule",
    "JitHostSyncRule",
    "JitTracedBranchRule",
    "JitUnhashableStaticRule",
]

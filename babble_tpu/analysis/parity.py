"""Cross-engine invariant parity: ``engine-parity``.

ROADMAP item 4 documents the failure mode this rule closes: the stack
has three engine surfaces (fused ``TpuHashgraph``, windowed
``WideHashgraph``, byzantine ``ForkHashgraph``) and every insert-path
protection — the PR-15 timestamp clamp, the retired-creator ingress
gate, WAL-before-gossip, quorum-helper routing, hostile snapshot-meta
checks — has historically been ported *by hand*, and the porting
already failed once (the fork engine's ingestion shipped without the
timestamp clamp; this rule fired on that gap on landing and the same
PR fixed it).

The check is a diff between a *declarative invariant registry* and
each engine surface's call closure over the PR-4 project graph:

- **engine surfaces** are project classes whose name ends with
  ``Hashgraph`` and that define (or inherit) ``insert_event``.
  ``Oracle``-named classes are exempt by design: oracles are the
  definition-first differential ground truths — they mirror semantics
  (see ``OracleHashgraph._eff_ts``) but are never on a trust boundary.
- each invariant names a **witness** (a call basename, a call-text
  suffix, or an attribute read) and a **scope**:

  - ``engine`` — the witness must appear in the closure of the
    engine's own ingest/tick anchors (``__init__``, ``insert_event``,
    ``flush``, ``run_consensus``, ``_run``, ``build_batch``,
    ``maybe_compact``), expanded through resolved call edges,
    attr-typed ``self.dag.insert`` hops, and *constructor expansion*
    (a call that resolves to a project class pulls that class's
    method bodies in — ``ForkConfig(...)`` exposes its
    ``super_majority`` property);
  - ``integration`` — the witness may instead live in an integration
    class (any project class holding an attribute constructor-typed to
    the engine, e.g. ``Core``): gates like WAL append and retired-
    creator refusal are deliberately engine-agnostic, and demanding
    them per-engine would force N copies of one seam;
  - ``adoption`` — for every ``load_snapshot``-named function whose
    forward closure *constructs* the engine, that closure must also
    reach a ``check*meta``-family bounds helper (vacuous when no
    adoption path builds the engine).

A missing witness is a finding anchored at the engine's
``insert_event`` (or class) line, so a genuinely-not-yet-ported
invariant is waived with a *named, justified* suppression there —
turning the ROADMAP drift list into a build-gated contract instead of
a prose promise.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .engine import FileContext, Finding, Rule
from .graph import FunctionInfo, ProjectContext

#: ingest/tick anchor methods whose closure IS the engine surface
_ANCHORS = ("__init__", "insert_event", "flush", "run_consensus",
            "_run", "build_batch", "maybe_compact")

_ENGINE_SUFFIX = "Hashgraph"
_ORACLE_MARK = "Oracle"

_META_CHECK_RE = re.compile(r"^_?check_(\w+_)?meta$|^check_meta$"
                            r"|^_?check_pending_entry$")


@dataclass(frozen=True)
class Invariant:
    """One insert-path protection every engine surface must witness."""

    name: str
    #: regex over call basenames (resolved callee or trailing text)
    call_re: Optional[str]
    #: regex over attribute-read names
    attr_re: Optional[str]
    #: regex over full dotted call text
    text_re: Optional[str]
    #: 'engine' | 'integration' (= engine closure OR integration class)
    scope: str
    rationale: str


#: the declarative registry the engine surfaces are diffed against —
#: adding a protection to one engine means adding its witness here,
#: which makes the OTHER engines fail lint until ported or waived
PARITY_REGISTRY: Tuple[Invariant, ...] = (
    Invariant(
        name="timestamp-clamp",
        call_re=r"^clamp_eff_ts$",
        attr_re=None,
        text_re=None,
        scope="engine",
        rationale=(
            "per-creator effective-timestamp clamp (core/dag.py "
            "clamp_eff_ts): without it a lying-clock creator skews "
            "every round-received median this surface commits"
        ),
    ),
    Invariant(
        name="retired-ingress-gate",
        call_re=r"retired",
        attr_re=r"retired",
        text_re=None,
        scope="integration",
        rationale=(
            "retired-creator ingress gate: events minted by a creator "
            "past its leave epoch must be refused at ingest, or a "
            "stale key keeps steering consensus after handoff"
        ),
    ),
    Invariant(
        name="wal-append",
        call_re=None,
        attr_re=None,
        text_re=r"(^|\.)wal\.append$",
        scope="integration",
        rationale=(
            "WAL append on the ingest path: an event adopted without "
            "a durable record is amnesia after crash-restart "
            "(wal-before-gossip covers the mint side; this covers "
            "the surface)"
        ),
    ),
    Invariant(
        name="quorum-helper-routing",
        call_re=r"^(supermajority|sync_quorum|attestation_quorum)$",
        attr_re=r"^(supermaj|super_majority)$",
        text_re=None,
        scope="engine",
        rationale=(
            "quorum thresholds must route through the shared helpers "
            "(membership/quorum.py): a hand-rolled 2n/3 forgets the "
            "+1 and admits a one-third-byzantine quorum "
            "(stale-quorum-math's interprocedural twin)"
        ),
    ),
    Invariant(
        name="hostile-meta-check",
        call_re=None,
        attr_re=None,
        text_re=None,        # special-cased: adoption-closure check
        scope="adoption",
        rationale=(
            "every load_snapshot path that constructs this engine "
            "must bounds-check the peer-supplied meta "
            "(_check_fork_meta/_check_host_meta family) before any "
            "array is materialized — the forged-snapshot OOM class"
        ),
    ),
)


def _basename(text: str) -> str:
    return text.rsplit(".", 1)[-1]


def _qual_basename(qual: str) -> str:
    return qual.rsplit(":", 1)[-1].rsplit(".", 1)[-1]


class _Witnesses:
    """Witness facts of one closure: call basenames, call texts,
    attribute-read names."""

    def __init__(self) -> None:
        self.call_names: Set[str] = set()
        self.call_texts: Set[str] = set()
        self.attr_names: Set[str] = set()

    def absorb(self, fi: FunctionInfo) -> None:
        for site in fi.calls:
            self.call_texts.add(site.text)
            self.call_names.add(_basename(site.text))
            for q in site.callees:
                self.call_names.add(_qual_basename(q))
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Attribute):
                self.attr_names.add(node.attr)

    def has(self, inv: Invariant) -> bool:
        if inv.call_re and any(re.search(inv.call_re, n)
                               for n in self.call_names):
            return True
        if inv.attr_re and any(re.search(inv.attr_re, n)
                               for n in self.attr_names):
            return True
        if inv.text_re and any(re.search(inv.text_re, t)
                               for t in self.call_texts):
            return True
        return False


def _closure_functions(
    project: ProjectContext, seeds: List[str],
) -> List[FunctionInfo]:
    """Seed qualnames expanded through every resolved call edge, plus
    constructor expansion: a call whose text resolves to a project
    class pulls in that class's own methods (NamedTuple configs carry
    their quorum properties; no ``__init__`` edge exists for them)."""
    out: List[FunctionInfo] = []
    seen: Set[str] = set()
    queue = list(seeds)
    while queue:
        q = queue.pop()
        if q in seen:
            continue
        seen.add(q)
        fi = project.functions.get(q)
        if fi is None:
            continue
        out.append(fi)
        mod = project.modules.get(fi.module)
        for site in fi.calls:
            queue.extend(site.callees)
            if mod is not None and site.text and "." not in site.text:
                key = project._resolve_class(mod, site.text)
                ci = project.classes.get(key) if key else None
                if ci is not None:
                    queue.extend(ci.methods.values())
    return out


def _engine_surfaces(project: ProjectContext):
    """(ClassInfo, insert_event qualname) for every engine surface."""
    for key, ci in sorted(project.classes.items()):
        if not ci.name.endswith(_ENGINE_SUFFIX):
            continue
        if _ORACLE_MARK in ci.name:
            continue
        ins = project.lookup_method(key, "insert_event")
        if ins is not None:
            yield ci, ins


class _ParityState:
    """Project-wide diff, computed once per run and cached like
    ``_determinism_state``."""

    def __init__(self, project: ProjectContext):
        #: (module, class) -> [(invariant, message)]
        self.missing: Dict[Tuple[str, str], List[Tuple[Invariant, str]]] = {}
        self._compute(project)

    def _compute(self, project: ProjectContext) -> None:
        surfaces = list(_engine_surfaces(project))
        if not surfaces:
            return
        loaders = [
            (qual, _closure_functions(project, [qual]))
            for qual, fi in sorted(project.functions.items())
            if fi.name == "load_snapshot"
        ]
        for ci, ins_qual in surfaces:
            seeds = []
            for anchor in _ANCHORS:
                meth = project.lookup_method(ci.key, anchor)
                if meth is not None:
                    seeds.append(meth)
            engine_w = _Witnesses()
            for fi in _closure_functions(project, seeds):
                engine_w.absorb(fi)
            integ_w = _Witnesses()
            for other in project.classes.values():
                holds = any(ci.key in cands
                            for cands in other.attr_types.values())
                if not holds or other.key == ci.key:
                    continue
                for meth_qual in other.methods.values():
                    fi = project.functions.get(meth_qual)
                    if fi is not None:
                        integ_w.absorb(fi)
            for inv in PARITY_REGISTRY:
                if inv.scope == "adoption":
                    msg = self._check_adoption(project, ci, loaders)
                    if msg:
                        self.missing.setdefault(ci.key, []).append(
                            (inv, msg))
                    continue
                ok = engine_w.has(inv)
                if not ok and inv.scope == "integration":
                    ok = integ_w.has(inv)
                if not ok:
                    where = ("its ingest/tick closure"
                             if inv.scope == "engine" else
                             "its ingest/tick closure or any "
                             "integration class holding it")
                    self.missing.setdefault(ci.key, []).append((inv, (
                        f"engine surface `{ci.name}` never witnesses "
                        f"insert-path invariant `{inv.name}` in {where} "
                        f"— {inv.rationale}; port the protection or "
                        "waive it here with a justified suppression"
                    )))

    @staticmethod
    def _check_adoption(project: ProjectContext, ci,
                        loaders) -> Optional[str]:
        """A load_snapshot closure that constructs this engine must
        also reach a check*meta helper."""
        for qual, closure in loaders:
            constructs = False
            checked = False
            for fi in closure:
                for site in fi.calls:
                    if _basename(site.text) == ci.name:
                        constructs = True
                    base = _basename(site.text)
                    if _META_CHECK_RE.match(base) or any(
                            _META_CHECK_RE.match(_qual_basename(q))
                            for q in site.callees):
                        checked = True
            if constructs and not checked:
                lname = _qual_basename(qual)
                return (
                    f"`{lname}` adopts peer-supplied snapshot bytes "
                    f"into `{ci.name}` without a check*meta-family "
                    "bounds pass in its closure — "
                    "invariant `hostile-meta-check`: a hostile meta "
                    "can size allocations before any signature is "
                    "looked at"
                )
        return None


class EngineParityRule(Rule):
    name = "engine-parity"
    description = (
        "every engine surface (class *Hashgraph with insert_event; "
        "oracles exempt) must witness the declarative insert-path "
        "invariant registry — timestamp clamp, retired-creator ingress "
        "gate, WAL append, quorum-helper routing, hostile meta checks "
        "— in its ingest/adoption call closure; a protection added to "
        "one engine fails lint on the others until ported or waived "
        "with a justified suppression"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        project: ProjectContext = ctx.project
        if project is None:
            return
        state = getattr(project, "_parity_state", None)
        if state is None:
            state = _ParityState(project)
            project._parity_state = state
        for key, misses in sorted(state.missing.items()):
            ci = project.classes.get(key)
            if ci is None:
                continue
            mod = project.modules.get(ci.module)
            if mod is None or mod.path != ctx.path:
                continue
            anchor = self._anchor(project, ci, mod)
            for _inv, msg in misses:
                yield self.finding(ctx, anchor, msg)

    @staticmethod
    def _anchor(project: ProjectContext, ci, mod) -> ast.AST:
        """The engine's own insert_event def when it has one, else its
        class statement — a line the surface's author owns, so a
        waiver suppression has a stable home."""
        own = ci.methods.get("insert_event")
        fi = project.functions.get(own) if own else None
        if fi is not None:
            return fi.node
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and node.name == ci.name:
                return node
        return ast.Pass(lineno=1, col_offset=0)

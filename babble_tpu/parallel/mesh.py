"""Device mesh construction.

One 2D mesh covers both parallel axes of the consensus computation:

- ``ev``  — event-axis sharding (sequence-parallel analogue; the DAG's
  long axis, up to 1M events per BASELINE.md).
- ``p``   — participant-axis sharding (tensor-parallel analogue; witness
  coordinate rows and vote matrices split by creator column).

On a real slice the mesh should be laid out so ``p`` rides the faster ICI
links (witness all-gathers are the chatty collective); ``jax.devices()``
order already reflects the physical torus for TPU backends.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh


def _factor(n: int) -> Tuple[int, int]:
    """Split n into (ev, p) with p the largest power-of-two factor <= sqrt(n)."""
    p = 1
    while n % (p * 2) == 0 and (p * 2) ** 2 <= n:
        p *= 2
    return n // p, p


def make_mesh(
    n_devices: Optional[int] = None,
    shape: Optional[Tuple[int, int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build the ("ev", "p") mesh over the first n_devices jax devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if n_devices > len(devices):
        raise ValueError(
            f"requested {n_devices} devices, only {len(devices)} available"
        )
    if shape is None:
        shape = _factor(n_devices)
    ev, p = shape
    if ev * p != n_devices:
        raise ValueError(f"mesh shape {shape} != device count {n_devices}")
    grid = np.asarray(devices[:n_devices]).reshape(ev, p)
    return Mesh(grid, ("ev", "p"))

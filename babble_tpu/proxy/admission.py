"""Admission control: the ingress plane's front door (ISSUE 6 (d)).

The submit path used to be an unbounded ``asyncio.Queue``: any client
could grow it without limit (memory), and a single bombarding client
could fill it faster than the node drains, starving every other
client's transactions (FIFO is fair only among equals).  The
:class:`AdmissionQueue` replaces it with:

- **bounded queues** — one FIFO per client, capped at ``per_client``,
  plus a ``total`` cap across clients;
- **load shedding** — a submit over either cap is rejected immediately
  with a structured :class:`OverloadedError` (JSON-RPC clients see
  ``{"code": "overloaded", "scope": ..., "retry_after_ms": ...}``)
  instead of queueing into unbounded latency;
- **round-robin fairness** — the node drains one transaction per
  client per turn, so a client bombarding at 100× the rate of the rest
  gets at most an equal share of minted-event payload slots and cannot
  starve anyone.

The surface mirrors the ``asyncio.Queue`` subset the node's select
loop uses (``get`` / ``get_nowait`` / ``qsize`` / ``empty``), so
``Node.run`` drains it unchanged.  ``put``/``put_nowait`` exist for
queue-compat callers (tests, dummy harnesses) and submit under a
shared anonymous client id — real ingress goes through
``submit_nowait(client, tx)`` with the connection's peer identity.
"""

from __future__ import annotations

import asyncio
import time
from collections import OrderedDict, deque
from typing import Deque, Dict, Optional

#: client id used by queue-compat ``put``/``put_nowait`` callers
ANON_CLIENT = "<anon>"

#: adaptive-cap EWMA window (seconds) and smoothing factor: the drain
#: rate is sampled per window and folded with DRAIN_ALPHA weight on the
#: newest sample — a few windows to converge, stable under bursts
DRAIN_WINDOW_S = 0.5
DRAIN_ALPHA = 0.3


class OverloadedError(Exception):
    """Structured load-shed rejection.  ``scope`` says which cap was
    hit (``client``: your own backlog; ``total``: the node's); clients
    must back off ``retry_after_ms`` before resubmitting."""

    def __init__(self, scope: str, depth: int, cap: int,
                 retry_after_ms: int = 100, admitted: int = 0):
        self.scope = scope
        self.depth = depth
        self.cap = cap
        self.retry_after_ms = retry_after_ms
        #: batched submits: how many txs of the batch WERE admitted
        #: before the cap tripped — the client resubmits only the rest
        self.admitted = admitted
        super().__init__(
            f"overloaded: {scope} submit queue at {depth}/{cap}"
        )

    def to_error(self) -> dict:
        """The JSON-RPC structured error body (jsonrpc.py serializes
        this verbatim; clients key off ``code``)."""
        return {
            "code": "overloaded",
            "scope": self.scope,
            "depth": self.depth,
            "cap": self.cap,
            "retry_after_ms": self.retry_after_ms,
            "admitted": self.admitted,
        }

    @classmethod
    def from_error(cls, err: dict) -> "OverloadedError":
        return cls(
            scope=str(err.get("scope", "total")),
            depth=int(err.get("depth", 0)),
            cap=int(err.get("cap", 0)),
            retry_after_ms=int(err.get("retry_after_ms", 100)),
            admitted=int(err.get("admitted", 0)),
        )


class AdmissionQueue:
    """Bounded, per-client-fair submit queue (see module docstring)."""

    def __init__(self, per_client: int = 1024, total: int = 8192,
                 registry=None, adaptive: bool = False,
                 horizon_s: float = 2.0, min_total: int = 64,
                 max_total: Optional[int] = None):
        """``adaptive=True`` (ROADMAP 1c leftover) derives the caps
        from the OBSERVED commit drain rate instead of static config:
        the queue admits at most ``horizon_s`` seconds of drain (EWMA
        over DRAIN_WINDOW_S samples), clamped to [min_total,
        max_total].  A node that drains 10k tx/s offers a deep queue; a
        node wedged behind consensus backpressure shrinks toward
        min_total and sheds — which is the point: queued work the node
        cannot drain is just latency the client pays.  The static
        ``per_client``/``total`` remain the COLD-START caps until the
        first drain window completes, and per-client fairness becomes a
        dynamic equal share of the effective total."""
        if per_client <= 0 or total <= 0:
            raise ValueError("admission caps must be positive")
        if adaptive and (horizon_s <= 0 or min_total <= 0):
            raise ValueError("adaptive admission bounds must be positive")
        self.per_client = per_client
        self.total = total
        self.adaptive = adaptive
        self.horizon_s = horizon_s
        self.min_total = min_total
        self.max_total = max_total if max_total is not None else total
        #: EWMA of the drain rate (tx/s); None until one window closes
        self._drain_ewma: Optional[float] = None
        self._win_start = time.monotonic()
        self._win_drained = 0
        #: client -> FIFO; OrderedDict preserves round-robin rotation
        #: order (move_to_end after each drain turn)
        self._queues: "OrderedDict[str, Deque[bytes]]" = OrderedDict()
        self._size = 0
        self._data = asyncio.Event()
        self._m_shed = None
        self._m_admitted = None
        #: attribution plane (ISSUE 11): the owning node's lineage and
        #: flight recorders, bound late like the registry — the front
        #: door records each tx's submit/admit/shed verdict and shed
        #: EPISODES land on the flight ring (rate-limited)
        self._lineage = None
        self._flight = None
        if registry is not None:
            self.instrument(registry)

    def bind_observability(self, lineage, flight) -> None:
        self._lineage = lineage
        self._flight = flight

    def instrument(self, registry) -> None:
        self._m_shed = registry.counter(
            "babble_ingress_shed_total",
            "submitted transactions rejected by admission control, by "
            "which cap tripped",
            labelnames=("scope",))
        for scope in ("client", "total"):
            self._m_shed.labels(scope)
        self._m_admitted = registry.counter(
            "babble_ingress_admitted_total",
            "submitted transactions accepted into the admission queue")
        registry.gauge(
            "babble_ingress_queue_depth",
            "transactions waiting in the admission queue across all "
            "clients",
        ).set_function(lambda: self._size)
        registry.gauge(
            "babble_ingress_clients",
            "clients with a non-empty admission queue",
        ).set_function(lambda: len(self._queues))
        registry.gauge(
            "babble_ingress_total_cap",
            "total admission cap in force (drain-rate-derived when "
            "adaptive, else the static config)",
        ).set_function(self.effective_total)
        registry.gauge(
            "babble_ingress_drain_rate",
            "EWMA of the observed drain rate (tx/s; 0 until the first "
            "adaptive window closes)",
        ).set_function(lambda: self._drain_ewma or 0.0)

    # ------------------------------------------------------------------
    # adaptive caps (drain-rate EWMA)

    def _note_drain(self, n: int = 1) -> None:
        """Fold drained txs into the rate EWMA.  Called by get_nowait
        (the drain side IS the observation point) and with n=0 by
        submit_nowait, so a FULLY wedged drain still closes windows and
        decays the rate toward zero — without that, a node that stopped
        draining would keep admitting at its last healthy cap."""
        if not self.adaptive:
            return
        self._win_drained += n
        now = time.monotonic()
        dt = now - self._win_start
        if dt >= DRAIN_WINDOW_S:
            if self._win_drained == 0 and self._size == 0:
                # IDLE window: nothing was queued, so nothing could
                # drain — a zero sample here is not evidence of a
                # wedged drain, and folding it would collapse the cap
                # to min_total on the first burst after any quiet
                # stretch.  Re-arm the window without sampling.
                self._win_start = now
                return
            rate = self._win_drained / dt
            self._drain_ewma = (
                rate if self._drain_ewma is None
                else DRAIN_ALPHA * rate
                + (1 - DRAIN_ALPHA) * self._drain_ewma
            )
            self._win_start = now
            self._win_drained = 0

    def effective_total(self) -> int:
        """The total cap in force: ``horizon_s`` seconds of observed
        drain when adaptive (clamped), else the static cap."""
        if not self.adaptive or self._drain_ewma is None:
            return self.total
        derived = int(self._drain_ewma * self.horizon_s)
        return max(self.min_total, min(derived, self.max_total))

    def effective_per_client(self) -> int:
        """Per-client cap: an equal share of the effective total across
        clients with backlog (floor 8 so a fresh client always gets a
        foot in the door), else the static cap."""
        if not self.adaptive or self._drain_ewma is None:
            return self.per_client
        share = self.effective_total() // max(1, len(self._queues))
        return max(8, share)

    # ------------------------------------------------------------------
    # ingress side

    def submit_nowait(self, client: str, tx: bytes) -> None:
        """Admit one transaction for ``client`` or shed it with a
        structured OverloadedError."""
        self._note_drain(0)   # close stale windows: no drain = decay
        if self._lineage is not None:
            self._lineage.note_tx(tx, "submit", client=client)
        total = self.effective_total()
        if self._size >= total:
            self._note_shed(tx, "total", self._size, total)
            raise OverloadedError("total", self._size, total)
        per_client = self.effective_per_client()
        q = self._queues.get(client)
        if q is not None and len(q) >= per_client:
            self._note_shed(tx, "client", len(q), per_client)
            raise OverloadedError("client", len(q), per_client)
        if q is None:
            q = deque()
            self._queues[client] = q
        q.append(tx)
        self._size += 1
        if self._m_admitted is not None:
            self._m_admitted.inc()
        if self._lineage is not None:
            self._lineage.note_tx(tx, "admit")
        self._data.set()

    def _note_shed(self, tx: bytes, scope: str, depth: int,
                   cap: int) -> None:
        if self._m_shed is not None:
            self._m_shed.labels(scope).inc()
        if self._lineage is not None:
            self._lineage.note_tx(tx, "shed", scope=scope)
        if self._flight is not None:
            # a shed EPISODE is one flight record, not one per tx — a
            # bombard burst must not evict the interesting transitions
            self._flight.note_limited("admission_shed", scope=scope,
                                      depth=depth, cap=cap)

    # queue-compat writers (tests / in-process harnesses)

    def put_nowait(self, tx: bytes) -> None:
        self.submit_nowait(ANON_CLIENT, tx)

    async def put(self, tx: bytes) -> None:
        self.put_nowait(tx)

    # ------------------------------------------------------------------
    # drain side (the node's select loop)

    def get_nowait(self) -> bytes:
        """Pop one transaction, round-robin across clients: the head
        client yields ONE tx and rotates to the tail, so every client
        with backlog advances at the same rate regardless of depth."""
        while self._queues:
            client, q = next(iter(self._queues.items()))
            if not q:
                # emptied by a previous turn: drop the bookkeeping row
                del self._queues[client]
                continue
            tx = q.popleft()
            self._size -= 1
            self._note_drain()
            if q:
                self._queues.move_to_end(client)
            else:
                del self._queues[client]
            if self._size == 0:
                self._data.clear()
            return tx
        self._data.clear()
        raise asyncio.QueueEmpty

    async def get(self) -> bytes:
        while True:
            try:
                return self.get_nowait()
            except asyncio.QueueEmpty:
                await self._data.wait()

    def qsize(self) -> int:
        return self._size

    def empty(self) -> bool:
        return self._size == 0

"""Fixture: asyncio interleaving race — shared attribute mutated on
both sides of an await with no lock held."""

import asyncio


class Pipeline:
    def __init__(self):
        self.pending = []
        self.core_lock = asyncio.Lock()

    async def drain_unlocked(self, items):
        self.pending = list(items)
        await asyncio.sleep(0)  # another task may run here
        self.pending = []  # MARK: await-state-race

    async def drain_locked(self, items):
        # clean: both writes happen under the lock
        async with self.core_lock:
            self.pending = list(items)
            await asyncio.sleep(0)
            self.pending = []

    async def drain_block_guard(self, items):
        # `block_writer` is NOT a lock — the `lock` inside `block` must
        # not exempt these writes (word-boundary matching)
        async with self.block_writer:
            self.pending = list(items)
            await asyncio.sleep(0)
            self.pending = []  # MARK: await-state-race

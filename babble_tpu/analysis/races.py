"""Asyncio shared-state race detection.

The gossip runtime (node/node.py, net/, fleet.py) is single-threaded
asyncio, so races here are not data races but *interleaving* races:
every ``await`` is a scheduling point where another coroutine of the
same node may run and observe or overwrite shared attributes.  The bug
shape this rule targets: a coroutine mutates ``self.x``, awaits, then
mutates ``self.x`` again — between the two writes the object is in a
state the author thought was private, and a second task entering the
same method corrupts it (lost updates, double-drains, torn multi-field
invariants).

A write is exempt when it happens under a held lock — any ``with`` /
``async with`` whose context expression mentions ``lock`` or ``mutex``
in an attribute/variable name (``async with self.core_lock:``).  The
await itself may be inside or outside the lock: holding a lock across
an await still yields the loop, but other writers of the same attr are
excluded, which is the invariant that matters.

**Interprocedural (v2)**: a call to ``self.helper(...)`` counts as a
write of every attribute in the helper's *transitive unlocked
self-write closure* (graph.ProjectContext.self_write_closure), at the
call site, under the caller's lock context.  Extracting the mutation
into a method no longer blinds the rule:

    async def refill(self):
        self._reset()            # _reset writes self.level -> "write"
        await self.pump.fill()
        self._reset()            # second write across the await: race

Helper writes performed under the helper's OWN lock are excluded from
the closure (they are serialized against other writers), which keeps
``_process_sync_response``-style lock-everything helpers clean.

Heuristic boundaries: statements are linearized in source order (a
write in an ``if`` arm counts as "before" a later await even when the
branch is not taken at runtime), and lock detection is by name.  Both
favor recall: a false positive documents itself with a named
suppression; a missed race corrupts a node.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from .engine import FileContext, Finding, Rule
from .graph import names_lock as _names_lock

# event: (kind, attr, node, locked, via) where via is the helper method
# name for closure-derived writes ("" for direct writes/awaits)
_Event = Tuple[str, str, ast.AST, bool, str]


class AwaitStateRaceRule(Rule):
    name = "await-state-race"
    description = (
        "coroutine mutates the same self.<attr> both before and after "
        "an await without holding a lock — directly or via called "
        "helpers — another task can interleave at the await and "
        "observe/clobber the intermediate state"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # class membership for self-call resolution: direct methods only
        # (a nested async def is its own schedule and owns no `self`)
        method_cls: Dict[int, str] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.AsyncFunctionDef):
                        method_cls[id(sub)] = node.name
        project = getattr(ctx, "project", None)
        module = (project.path_module.get(ctx.path)
                  if project is not None else None)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                yield from self._check_coroutine(
                    ctx, node, project, module, method_cls.get(id(node)))

    def _helper_writes(self, project, module: Optional[str],
                       cls: Optional[str], method: str) -> frozenset:
        """Transitive unlocked self-write set of self.<method>()."""
        if project is None or module is None or cls is None:
            return frozenset()
        qual = project.lookup_method((module, cls), method)
        if qual is None:
            return frozenset()
        return frozenset(project.self_write_closure(qual))

    def _check_coroutine(
        self, ctx: FileContext, fn: ast.AsyncFunctionDef,
        project, module: Optional[str], cls: Optional[str],
    ) -> Iterator[Finding]:
        self._project = project
        self._module = module
        self._cls = cls
        events: List[_Event] = []
        self._collect(fn.body, locked=False, events=events)

        seen_await_after_write: Dict[str, ast.AST] = {}
        pending: Dict[str, ast.AST] = {}
        for kind, attr, node, locked, via in events:
            if kind == "await":
                for a, n in pending.items():
                    seen_await_after_write.setdefault(a, n)
                pending.clear()
                continue
            if locked:
                continue
            if attr in seen_await_after_write:
                how = (f" (write via call to `self.{via}()`)" if via
                       else "")
                yield self.finding(
                    ctx, node,
                    f"self.{attr} is written both before (line "
                    f"{seen_await_after_write[attr].lineno}) and after an "
                    f"await in `{fn.name}` without a lock{how} — an "
                    "interleaving task sees the intermediate state",
                )
                # report once per attr per coroutine
                del seen_await_after_write[attr]
                continue
            pending.setdefault(attr, node)

    def _collect(self, body: List[ast.stmt], locked: bool,
                 events: List[_Event]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes have their own schedule
            self._collect_stmt(stmt, locked, events)

    def _awaits_in(self, expr: ast.AST, locked: bool,
                   events: List[_Event]) -> None:
        for node in ast.walk(expr):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Await):
                events.append(("await", "", node, locked, ""))

    def _self_calls_in(self, expr: ast.AST, locked: bool,
                       events: List[_Event]) -> None:
        """Closure-derived writes: `self.m(...)` writes everything m
        (transitively) writes on self outside a lock."""
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                for attr in sorted(self._helper_writes(
                        self._project, self._module, self._cls,
                        node.func.attr)):
                    events.append(
                        ("write", attr, node, locked, node.func.attr))
            stack.extend(ast.iter_child_nodes(node))

    def _collect_stmt(self, stmt: ast.stmt, locked: bool,
                      events: List[_Event]) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._awaits_in(item.context_expr, locked, events)
                self._self_calls_in(item.context_expr, locked, events)
            if isinstance(stmt, ast.AsyncWith):
                # `async with x:` awaits __aenter__ even without an
                # explicit Await node in the source
                events.append(("await", "", stmt, locked, ""))
            inner_locked = locked or any(
                _names_lock(item.context_expr) for item in stmt.items
            )
            self._collect(stmt.body, inner_locked, events)
        elif isinstance(stmt, (ast.If, ast.While)):
            self._awaits_in(stmt.test, locked, events)
            self._self_calls_in(stmt.test, locked, events)
            self._collect(stmt.body, locked, events)
            self._collect(stmt.orelse, locked, events)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._awaits_in(stmt.iter, locked, events)
            self._self_calls_in(stmt.iter, locked, events)
            if isinstance(stmt, ast.AsyncFor):
                events.append(("await", "", stmt, locked, ""))
            self._collect(stmt.body, locked, events)
            self._collect(stmt.orelse, locked, events)
        elif isinstance(stmt, ast.Try):
            self._collect(stmt.body, locked, events)
            for h in stmt.handlers:
                self._collect(h.body, locked, events)
            self._collect(stmt.orelse, locked, events)
            self._collect(stmt.finalbody, locked, events)
        else:
            # simple statement: awaits evaluate before the binding lands
            self._awaits_in(stmt, locked, events)
            self._self_calls_in(stmt, locked, events)
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                for t in targets:
                    self._collect_write(t, stmt, locked, events)

    def _collect_write(self, target: ast.AST, stmt: ast.stmt, locked: bool,
                       events: List[_Event]) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._collect_write(elt, stmt, locked, events)
        elif isinstance(target, ast.Starred):
            self._collect_write(target.value, stmt, locked, events)
        elif (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            events.append(("write", target.attr, stmt, locked, ""))

"""Native (C++) host components, loaded via ctypes.

The reference is pure Go with no native layer (SURVEY.md §2); here the
performance-critical host-side pieces — bulk DAG generation and level
scheduling for simulation/benchmark scale — are C++, compiled on first use
with the toolchain baked into the image.  Every native entry point has a
pure-Python/numpy fallback with identical output (differentially tested),
so the framework works even without a compiler.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from pathlib import Path
from typing import Optional

_DIR = Path(__file__).parent
_BUILD = _DIR / "_build"

_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _compile(src: Path, out: Path) -> None:
    out.parent.mkdir(exist_ok=True)
    # build into a temp file then rename: concurrent processes (a testnet
    # fleet booting) must never dlopen a half-written .so
    fd, tmp = tempfile.mkstemp(dir=str(out.parent), suffix=".so")
    os.close(fd)
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        str(src), "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load() -> Optional[ctypes.CDLL]:
    """The graph-builder library, or None if no toolchain is available."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    src = _DIR / "graph_builder.cpp"
    so = _BUILD / "graph_builder.so"
    try:
        if not so.exists() or so.stat().st_mtime < src.stat().st_mtime:
            _compile(src, so)
        lib = ctypes.CDLL(str(so))
    except (OSError, subprocess.SubprocessError):
        return None

    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    u8p = ctypes.POINTER(ctypes.c_uint8)

    lib.gossip_dag.restype = ctypes.c_long
    lib.gossip_dag.argtypes = [
        ctypes.c_uint64, ctypes.c_int32, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64,
        i32p, i32p, i32p, i32p, i64p, u8p, i32p, i32p,
    ]
    lib.build_schedule.restype = ctypes.c_int32
    lib.build_schedule.argtypes = [
        i32p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, i32p, i32p,
    ]
    lib.max_level_width.restype = ctypes.c_int32
    lib.max_level_width.argtypes = [i32p, ctypes.c_int64, ctypes.c_int32, i32p]

    _lib = lib
    return _lib


def available() -> bool:
    return load() is not None

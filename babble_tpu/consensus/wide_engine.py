"""WideHashgraph: the windowed wide pipeline behind the live Core surface.

VERDICT r4 missing #4: `stream_consensus` (ops/stream.py) was a batch
driver fed by generator-oracle knowledge a live node cannot have — the
suffix-min of future parent slots and the whole-stream head seqs.  This
engine replaces those inputs with the **seq_window contract** the
stream docstring promises (ops/stream.py "Eviction safety"):

- eviction keeps every creator's last ``seq_window`` events relative to
  its CURRENT head (the only head a live node knows), exactly the
  reference's rolling-cache bound (hashgraph/caches.go:45-76);
- a peer referencing anything older gets TooLateError through the sync
  path (core/dag.py participant_events) and must fast-forward — the
  same contract the fused live engine (consensus/engine.py) ships;
- there is no ``min_future_parent`` oracle: an arriving event whose
  parent fell below the window is rejected at insert (HostDag refuses
  unknown parents), which is what the reference's ErrTooLate does.

Fame mid-stream uses the witness-set finality gate (ops/wide.py
``complete=False``): a round decides only once every chain's head round
passed it, so a late witness can never reopen a decided round and the
committed order is scheduling-invariant.  The cost is the documented
all-chains-must-mint liveness assumption (ops/wide.py _head_round_min).

Bit-parity: tests/test_wide_engine.py drives the same playbook through
this engine and the fused TpuHashgraph and pins identical committed
order, round_received and consensus timestamps at a forced-blocked
small shape.

Why this engine exists: the fused DagState holds la/fd as [E+1, N]
arrays — at the 10k-participant BASELINE scale that is the whole HBM.
The wide engine holds them as per-block column slices (ops/wide.py)
with window capacities fixed at construction, so a live wide-N node
runs in bounded memory with bounded jit shapes forever.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..common import OffsetList
from ..core.dag import HostDag
from ..core.event import Event
from ..obs import SIZE_BUCKETS
from ..ops.ingest import EventBatch
from ..ops.state import DagConfig, bucket as _bucket
from ..ops.stream import WideStream, _padded_schedule
from .engine import TpuHashgraph

INT64_MAX = np.iinfo(np.int64).max


class WideHashgraph(TpuHashgraph):
    """Live honest-mode engine over the blocked rolling window.

    Capacities are FIXED at construction (cfg.e_cap = window capacity,
    cfg.s_cap = in-window chain depth): the wide pipeline's shapes are
    its memory contract, so instead of growing, the engine compacts —
    and raises if a batch cannot fit even after compaction (the node
    is misconfigured for its traffic, not transiently unlucky)."""

    # no fused coordinate tensors -> no latency kernel; the inherited
    # dispatcher always takes the three-phase branch through this
    # class's divide_rounds/decide_fame/find_order (mid-stream fame
    # already runs behind its own witness-set gate, complete=False)
    KERNEL_SPLIT = False
    kernel_class = "throughput"
    finality_gate = False

    def __init__(
        self,
        participants: Dict[str, int],
        commit_callback: Optional[Callable[[List[Event]], None]] = None,
        verify_signatures: bool = True,
        e_cap: int = 4096,
        s_cap: int = 128,
        r_cap: int = 32,
        n_blocks: Optional[int] = None,
        auto_compact: bool = True,
        seq_window: int = 64,
        round_margin: int = 1,
        compact_min: Optional[int] = None,
        consensus_window: Optional[int] = None,
        coord8: bool = False,
        registry=None,
    ):
        # no super().__init__: it would allocate the fused [E+1, N]
        # la/fd tensors this engine exists to avoid
        n = len(participants)
        self.participants = participants
        self.commit_callback = commit_callback
        self.dag = HostDag(participants, verify_signatures=verify_signatures)
        self.cfg = DagConfig(n=n, e_cap=e_cap, s_cap=s_cap, r_cap=r_cap,
                             coord8=coord8)
        self.auto_compact = auto_compact
        self.seq_window = seq_window
        self.round_margin = round_margin
        self.compact_min = compact_min if compact_min is not None else max(
            e_cap // 4, 32
        )
        self.consensus_window = consensus_window

        self.stream = WideStream(
            self.cfg, n_blocks=n_blocks, round_margin=round_margin,
            seq_window=seq_window, record_ordered=False,
            registry=registry,
        )
        self.state = self.stream.state
        # flush telemetry (ISSUE 2 tentpole): how many events each
        # drained batch carries and how long the device-side coords
        # phase takes per drain — the per-sync device cost /Stats's
        # averages could never attribute
        reg = self.stream.registry
        self._m_flush_events = reg.histogram(
            "babble_wide_flush_events",
            "host events drained per wide-engine flush",
            buckets=SIZE_BUCKETS,
        )
        self._m_flush_seconds = reg.histogram(
            "babble_wide_flush_seconds",
            "wide-engine flush wall time (pad + device coords phase)",
        )

        self.consensus = OffsetList()
        from .digest import CommitDigest
        self._digest = CommitDigest()
        self.inactive_rounds = None   # per-creator eviction: fused-only
        self._evicted_creators_cache = 0
        self.consensus_transactions = 0
        self.last_committed_round_events = 0
        self._received: set = set()
        self._ordered_total = 0
        self._view: Dict[str, np.ndarray] = {}
        self._lcr_cache = -1
        self._r_off = 0

    def rebind_registry(self, registry) -> None:
        """Re-register flush + stage histograms on ``registry`` (called
        by Core after adopting this engine from a fast-forward snapshot
        or a checkpoint resume — the restore path builds engines with a
        private registry, so without the rebind the flush series
        silently drop off the node's /metrics)."""
        self.stream.rebind_registry(registry)
        self._m_flush_events = registry.histogram(
            "babble_wide_flush_events",
            "host events drained per wide-engine flush",
            buckets=SIZE_BUCKETS,
        )
        self._m_flush_seconds = registry.histogram(
            "babble_wide_flush_seconds",
            "wide-engine flush wall time (pad + device coords phase)",
        )

    # ------------------------------------------------------------------
    # ingest

    def flush(self) -> None:
        """Drain pending host events through the blocked coords phase."""
        if not self.dag.pending:
            return
        t_flush = time.perf_counter()
        k = len(self.dag.pending)
        if self.stream.n_live + k > self.cfg.e_cap:
            # compaction under pending events is safe up to the smallest
            # slot they still reference as a parent — the same bound the
            # stream driver calls min_future_parent
            min_parent = min(
                (p for s in self.dag.pending
                 for p in (self.dag.sp_slot[s], self.dag.op_slot[s])
                 if p >= 0),
                default=INT64_MAX,
            )
            self.maybe_compact(force=True, min_future_parent=min_parent)
            if self.stream.n_live + k > self.cfg.e_cap:
                raise ValueError(
                    f"batch of {k} events overflows the window "
                    f"({self.stream.n_live} live / {self.cfg.e_cap} cap) "
                    "even after compaction — raise e_cap or gossip less "
                    "per sync"
                )
        # in-window chain depth must fit the ce table (ops/stream.py).
        # Checked BEFORE the queue is drained: a raise after the drain
        # would strand the batch outside both the host queue and the
        # device window, leaving the engine silently corrupted — the
        # refused batch must stay pending so the caller can recover
        # (raise s_cap via a rebuilt engine, or gossip smaller syncs).
        sp, op, creator, seq, ts, mbit, sched = self.dag.peek_pending()
        s_off = np.asarray(self.state.s_off[: self.cfg.n])
        depth = int(np.max(seq - s_off[creator], initial=0))
        if depth >= self.cfg.s_cap:
            raise ValueError(
                f"in-window chain depth {depth} >= s_cap {self.cfg.s_cap}:"
                " raise s_cap or shrink seq_window"
            )
        self.dag.drop_pending()

        kpad = _bucket(k)
        t, b = sched.shape
        sched_p = np.full((-(-t // 64) * 64, _bucket(b, 1)), -1, np.int32)
        sched_p[:t, :b] = sched

        def pad1(a, fill, dtype):
            out = np.full(kpad, fill, dtype)
            out[:k] = a
            return out

        batch = EventBatch(
            sp=jnp.asarray(pad1(sp, -1, np.int32)),
            op=jnp.asarray(pad1(op, -1, np.int32)),
            creator=jnp.asarray(pad1(creator, 0, np.int32)),
            seq=jnp.asarray(pad1(seq, 0, np.int32)),
            ts=jnp.asarray(pad1(ts, 0, np.int64)),
            mbit=jnp.asarray(pad1(mbit, False, bool)),
            k=jnp.asarray(k, jnp.int32),
            sched=jnp.asarray(sched_p),
        )
        # window-wide fd sweep schedule: all live rows (stream batches
        # keep gaining first-descendants until every chain holds one)
        base = self.dag.slot_base
        levels_live = np.fromiter(
            (self.dag.levels[s] for s in range(base, self.dag.n_events)),
            np.int64, self.dag.n_events - base,
        )
        fd_slot_sched = jnp.asarray(
            _padded_schedule(levels_live, self.cfg.e_cap)
        )
        self.stream.ingest(batch, fd_slot_sched=fd_slot_sched)
        self.state = self.stream.state
        self._view = {}
        self._m_flush_events.observe(k)
        self._m_flush_seconds.observe(time.perf_counter() - t_flush)

    # ------------------------------------------------------------------
    # consensus pipeline (Core.run_consensus calls these in order)

    def divide_rounds(self) -> None:
        self.flush()

    def decide_fame(self) -> None:
        pass  # rounds+fame+order run together in find_order

    def find_order(self) -> List[Event]:
        self.flush()
        if self.stream.n_live == 0:
            return []
        self.stream.consensus(final=False)
        self.state = self.stream.state
        self._view = {}

        rr = self._arr("rr")
        cts = self._arr("cts")
        base = self.dag.slot_base
        ne = self.dag.n_events - base
        self._lcr_cache = int(self.state.lcr)
        self._r_off = int(self.state.r_off)
        new_slots = [
            s for s in range(ne)
            if rr[s] >= 0 and (base + s) not in self._received
        ]
        if not new_slots:
            if self.auto_compact:
                self.maybe_compact()
            return []

        new_events: List[Event] = []
        for s in new_slots:
            ev = self.dag.events[base + s]
            ev.round_received = int(rr[s])
            ev.consensus_timestamp = int(cts[s])
            new_events.append(ev)
            self._received.add(base + s)
        self._ordered_total += len(new_slots)

        from .ordering import consensus_sort

        new_events = consensus_sort(new_events, self._round_prn)
        for ev in new_events:
            self.consensus.append(ev.hex())
            self._digest.note(ev.hex())
            self.consensus_transactions += len(ev.transactions)

        lcr = self._lcr_cache
        if lcr >= 1:
            rounds = self._arr("round")
            self.last_committed_round_events = int(
                np.count_nonzero(rounds[:ne] == lcr - 1)
            )
        if self.commit_callback is not None and new_events:
            self.commit_callback(new_events)
        if self.auto_compact:
            self.maybe_compact()
        return new_events

    # ------------------------------------------------------------------
    # rolling window — the live seq_window contract (module docstring)

    def maybe_compact(self, force: bool = False,
                      min_future_parent: int = INT64_MAX) -> int:
        if self.dag.pending and min_future_parent == INT64_MAX:
            # pending events still reference parents by slot: without a
            # bound on their smallest parent, eviction could strand them
            return 0
        ne = self.stream.n_live
        if ne == 0:
            return 0
        k = self.stream.compact(
            min_future_parent=min_future_parent,  # live: no future oracle
            head_seqs=None,                # current heads (state.cnt - 1)
            compact_min=1 if force else self.compact_min,
        )
        self.state = self.stream.state   # compact donates the old state
        self._view = {}
        if k == 0:
            return 0
        base = self.dag.slot_base
        self.dag.evict_prefix(base + k)
        self._received = {g for g in self._received if g >= base + k}
        self._r_off = int(self.state.r_off)
        if self.consensus_window is not None:
            self.consensus.evict_to(
                max(self.consensus.start,
                    len(self.consensus) - self.consensus_window)
            )
            self._digest.evict_to(self.consensus.start)
        return k

    # ------------------------------------------------------------------
    # unsupported fused-only surface

    def _ensure_capacity(self, k_new: int) -> None:  # pragma: no cover
        raise NotImplementedError(
            "WideHashgraph capacities are fixed at construction"
        )

    def _unsupported(self, name: str):
        raise NotImplementedError(
            f"{name} needs the fused [E,N] coordinate tensors; the wide "
            "engine holds them as column blocks (use TpuHashgraph for "
            "predicate-level queries)"
        )

    def ancestor(self, x: str, y: str) -> bool:
        self._unsupported("ancestor")

    def see(self, x: str, y: str) -> bool:
        self._unsupported("see")

    def strongly_see(self, x: str, y: str) -> bool:
        self._unsupported("strongly_see")

    def oldest_self_ancestor_to_see(self, x: str, y: str) -> str:
        self._unsupported("oldest_self_ancestor_to_see")

"""Host-orchestrated, column-blocked consensus pipeline for wide
participant axes.

Why this exists — four XLA:TPU memory behaviors, all measured as real
OOMs on one 16 GB v5e at the 10k-participant configs (VERDICT r2
missing #1):

1. A gather operand inside ANY device loop (while/scan/fori) gets a
   layout-transposed copy of the WHOLE operand when it is loop-invariant
   (hoisting turns an unchanged carry back into an invariant).
2. Even a straight-line gather pays a one-operand-sized relayout temp.
3. A donated argument that merely passes through a program costs a
   flaky full-size copy; gather+scatter of one donated operand in one
   program copy-protects it (XLA cannot prove disjointness).
4. Multi-GB scan carries are double-buffered.

The la/fd coordinate tensors are [E+1, N] — 4.5 GB each at 10k x 450k
even in int8 — so "one operand" is most of the chip.  The fix with
teeth: **store them column-blocked**, as C separate arrays of shape
[E+1, ceil(N/C)].  Every consensus reduction is independent or
accumulative across the participant axis, so each program touches one
block and every hidden copy is bounded by ~coord_bytes/C:

- la/fd level scans: column-independent recurrences — one fused
  lax.scan program per block (double-buffer = one block).
- strongly-see counts (frontier march, fame voting): per-block partial
  counts accumulated into an [N, N] i32 tally (sum over chain blocks —
  exactly the psum-over-"p" decomposition of parallel/sharded.py, with
  blocks standing in for shards on a single chip).
- round-received / median timestamps: per-block partial see-counts and
  per-block timestamp columns, concatenated only at [chunk, N] size.

Loops live on the host (step programs + host loop, like a training
loop); loop-control scalars sync once per step, and the loops throttle
every few dispatches because enqueued programs allocate their outputs
at dispatch time.

Bit-parity with the fused single-jit pipeline is pinned by
tests/test_wide.py at small shapes with forced blocking.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import fame as fame_ops
from . import ingest as ingest_ops
from . import order as order_ops
from .ingest import EventBatch
from .ss import ss_counts_compare, ss_counts_onehot
from .state import (
    DagConfig,
    DagState,
    I32,
    init_state,
    sanitize,
    set_sentinel,
)

INT64_MAX = jnp.iinfo(jnp.int64).max

# target bytes per coordinate block; a gather relayout temp is bounded
# by this, so keep it well under the post-residency headroom
BLOCK_TARGET_BYTES = 1 << 30


def wide_wins(cfg: DagConfig) -> bool:
    """Same working-set bound as ops.fame.fame_mode."""
    return fame_ops.fame_mode(cfg) == "block"


def block_count(cfg: DagConfig) -> int:
    bytes_per = (cfg.e_cap + 1) * cfg.n * np.dtype(cfg.coord_dtype).itemsize
    return max(1, -(-bytes_per // BLOCK_TARGET_BYTES))


def _block_width(cfg: DagConfig, C: int) -> int:
    return -(-cfg.n // C)


def _use_onehot_partial(cfg: DagConfig) -> bool:
    """Per-block strongly-see partial: int8 one-hot MXU vs VPU compare.
    The one-hot pays an (s_cap+1)-fold flop redundancy but runs ~570x
    faster (394 int8 Tops vs the measured 0.69 Tops XLA compare-reduce),
    so it wins until chains get very deep.  Measured at N=10k: 0.47 s vs
    1.44 s at S=32; 2.2x at S=93."""
    return (jax.default_backend() == "tpu" and cfg.n >= 4096
            and cfg.s_cap <= 512)


@functools.lru_cache(maxsize=8)
def _jits(cfg: DagConfig, C: int):
    """Per-(config, block-count) jitted step programs."""
    n, e_cap, s_cap, r_cap = cfg.n, cfg.e_cap, cfg.s_cap, cfg.r_cap
    w = _block_width(cfg, C)
    sm = cfg.super_majority
    cd = cfg.coord_dtype
    e_row = jnp.arange(e_cap + 1) == e_cap

    # ---------------- coords ----------------

    def _write_batch(state, batch):
        # la/fd are block arrays, never part of `state` here
        return ingest_ops._write_batch_fields(state, cfg, batch)

    write_batch = jax.jit(_write_batch, donate_argnums=(0,))

    def _la_block_scan(sp, op, creator, seq, la_blk, slot_sched, blk_off):
        """Whole-schedule la fill for one column block (fused scan; the
        double-buffered carry is one block)."""
        col = jnp.arange(w)

        def step(la, idx):
            spx = sanitize(sp[idx], e_cap)
            opx = sanitize(op[idx], e_cap)
            rows = jnp.maximum(la[spx], la[opx])             # [B, w]
            own = creator[idx] - blk_off                     # block-local col
            own_here = (own >= 0) & (own < w)
            rows = jnp.where(
                own_here[:, None] & (col[None, :] == own[:, None]),
                seq[idx, None].astype(rows.dtype), rows,
            )
            return la.at[idx].set(rows), None

        la_blk, _ = jax.lax.scan(step, la_blk, slot_sched)
        return set_sentinel(la_blk, e_row[:, None], -1)

    la_block_scan = jax.jit(_la_block_scan, donate_argnums=(4,))

    def _fd_block_scan(sp, op, creator, seq, b_seq, b_k, n_events,
                       fd_blk, slot_sched, blk_off):
        """Whole-schedule reversed fd fill for one column block,
        including the own-seq seeding (_fd_init_own's block slice)."""
        kpad = b_seq.shape[0]
        pos = jnp.arange(kpad, dtype=I32)
        real = pos < b_k
        slots = jnp.where(real, n_events - b_k + pos, e_cap)
        own = jnp.where(real, creator[slots] - blk_off, -1)
        own_here = (own >= 0) & (own < w)
        fd_blk = fd_blk.at[
            jnp.where(own_here, slots, e_cap),
            jnp.clip(own, 0, w - 1),
        ].set(b_seq.astype(fd_blk.dtype))

        def step(fd, idx):
            rows = fd[idx]                                   # [B, w]
            spx = sanitize(sp[idx], e_cap)
            opx = sanitize(op[idx], e_cap)
            fd = fd.at[spx].min(rows)
            return fd.at[opx].min(rows), None

        fd_blk, _ = jax.lax.scan(step, fd_blk, slot_sched[::-1])
        return set_sentinel(fd_blk, e_row[:, None], cfg.fd_inf)

    fd_block_scan = jax.jit(_fd_block_scan, donate_argnums=(7,))

    def _coord_sent(state):
        return ingest_ops._reset_coord_sentinels(
            state, cfg, include_coords=False
        )

    coord_sent = jax.jit(_coord_sent, donate_argnums=(0,))

    # ---------------- blocked strongly-see partials ----------------

    def _ss_partial(rows_a, rows_b, acc):
        """acc += |{k in block : rows_a[a,k] >= rows_b[b,k]}| — exact
        per-block partial of the strongly-see count."""
        if _use_onehot_partial(cfg):
            part = ss_counts_onehot(rows_a, rows_b, s_cap)
        else:
            part = ss_counts_compare(rows_a, rows_b)
        return acc + part

    ss_partial = jax.jit(_ss_partial, donate_argnums=(2,))

    def _gather_rows(blk, idx):
        """[A, w] rows of one coordinate block (sentinel row for idx<0)."""
        return blk[sanitize(idx, e_cap)]

    gather_rows = jax.jit(_gather_rows)

    # ---------------- frontier march ----------------

    def _frontier_prep(state):
        cnt = state.cnt[:n] - state.s_off[:n]
        pos0 = jnp.where(cnt > 0, 0, jnp.iinfo(I32).max)
        pos_table0 = jnp.full((r_cap + 1, n), jnp.iinfo(I32).max, I32)
        pos_table0 = pos_table0.at[0].set(pos0)
        return cnt, pos0, pos_table0

    frontier_prep = jax.jit(_frontier_prep)

    def _round_witnesses(state, cnt, pos):
        valid_w = pos < cnt
        ws = state.ce[:n][jnp.arange(n), jnp.clip(pos, 0, s_cap)]
        return jnp.where(valid_w, ws, -1), valid_w

    round_witnesses = jax.jit(_round_witnesses)

    def _bisect_candidates(state, lo, hi):
        mid = (lo + hi) >> 1
        xs = state.ce[:n][jnp.arange(n), jnp.clip(mid, 0, s_cap)]
        return mid, xs

    bisect_candidates = jax.jit(_bisect_candidates)

    def _bisect_update(cnt_ab, valid_w, lo, hi, mid, chains_cnt):
        ss = (cnt_ab >= sm) & valid_w[None, :]
        ok = ss.sum(-1) >= sm
        active = lo < hi
        hi = jnp.where(ok & active, mid, hi)
        lo = jnp.where(~ok & active, mid + 1, lo)
        return lo, hi

    bisect_update = jax.jit(_bisect_update)

    def _col_gather(v, blk_off, fill=None):
        """Block-columns of a length-n vector via clipped gather — a
        dynamic_slice would clamp its start on the ragged last block and
        misalign every column."""
        cols = blk_off + jnp.arange(w)
        out = v[jnp.clip(cols, 0, v.shape[0] - 1)]
        if fill is not None:
            out = jnp.where(cols < n, out, fill)
        return out

    def _inherit_block(fde_blk, blk_off, s_off):
        """Per-block descent inheritance: min over witnesses of their
        first-inc events' fd rows, window-localized."""
        m = fde_blk.min(axis=0).astype(I32)                  # [w] absolute
        off = _col_gather(s_off, blk_off)
        return jnp.where(
            m >= int(cfg.fd_inf), jnp.iinfo(I32).max, m - off
        )

    inherit_block = jax.jit(_inherit_block)

    def _frontier_next(cnt, pos, pos_table, r, s_star, found, inherit):
        pos_next = jnp.minimum(
            jnp.where(found, s_star, jnp.iinfo(I32).max), inherit
        )
        pos_next = jnp.maximum(pos_next, pos)  # monotone safety
        any_next = (pos_next < cnt).any()
        pos_table = pos_table.at[jnp.minimum(r + 1, r_cap)].set(pos_next)
        return pos_next, pos_table, any_next

    frontier_next = jax.jit(_frontier_next, donate_argnums=(2,))

    def _frontier_fin(state, pos_table):
        state = ingest_ops.frontier_finalize(state, cfg, pos_table)
        return ingest_ops._reset_round_sentinels(state, cfg)

    frontier_fin = jax.jit(_frontier_fin, donate_argnums=(0,))

    # ---------------- fame ----------------

    def _wrow(tab, r_loc):
        return jax.lax.dynamic_slice_in_dim(tab, r_loc, 1, 0)[0]

    def _fame_wits(state, i):
        """Witness slots/validity for rounds i (subject), i-1 unused."""
        ws = _wrow(state.wslot, i)
        return ws, ws >= 0

    fame_wits = jax.jit(_fame_wits)

    def _votes0_block(la1_blk_rows, seqw_i, blk_off, valid_1, valid_i):
        """Block-columns of the d=1 direct see votes."""
        sw = _col_gather(seqw_i, blk_off)
        vi = _col_gather(valid_i, blk_off, fill=False)
        return (
            (la1_blk_rows >= sw[None, :])
            & valid_1[:, None] & vi[None, :]
        ).astype(jnp.float32)

    votes0_block = jax.jit(_votes0_block)

    def _fame_tally(cnt_ab, valid_j, valid_p, valid_i, votes, famous_i,
                    mb_j, d):
        ss = ((cnt_ab >= sm) & valid_j[:, None] & valid_p[None, :]
              ).astype(jnp.float32)
        tot = ss.sum(-1)
        yays = jax.lax.dot_general(
            ss.astype(jnp.bfloat16), votes.astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        nays = tot[:, None] - yays
        v = yays >= nays
        strong = jnp.maximum(yays, nays) >= sm
        normal = (d % cfg.active_n) != 0

        deciding = strong & normal
        decide_x = deciding.any(axis=0)
        v_star = (deciding & v).any(axis=0)
        und = (famous_i == fame_ops.FAME_UNDEFINED) & valid_i
        famous_i = jnp.where(
            und & decide_x,
            jnp.where(v_star, fame_ops.FAME_TRUE,
                      fame_ops.FAME_FALSE).astype(jnp.int8),
            famous_i,
        )
        coin_vote = jnp.where(strong, v, mb_j[:, None])
        votes = jnp.where(normal, v, coin_vote).astype(jnp.float32)
        und2 = (famous_i == fame_ops.FAME_UNDEFINED) & valid_i
        return votes, famous_i, und2.any()

    fame_tally = jax.jit(_fame_tally, donate_argnums=(4,))

    def _fame_write(famous_tab, famous_i, i):
        return jax.lax.dynamic_update_slice_in_dim(
            famous_tab, famous_i[None, :], i, 0
        )

    fame_write = jax.jit(_fame_write)

    def _fame_fin(state, famous_out):
        return fame_ops.fame_advance_lcr(cfg, state, famous_out)

    fame_fin = jax.jit(_fame_fin)

    # ---------------- order ----------------

    def _order_prep(state):
        R = r_cap
        wsl = state.wslot[:R]
        valid_w = wsl >= 0
        seqw = state.seq[sanitize(wsl, e_cap)]
        fam = (state.famous[:R] == fame_ops.FAME_TRUE) & valid_w
        decided = (
            (~valid_w) | (state.famous[:R] != fame_ops.FAME_UNDEFINED)
        ).all(axis=1)
        has_w = valid_w.any(axis=1)
        fam_cnt = fam.sum(axis=1)
        und = order_ops.order_undetermined(cfg, state)
        return seqw, fam, decided, has_w, fam_cnt, und

    order_prep = jax.jit(_order_prep)

    def _sees_partial_block(fd_blk, seqw_i, fam_i, blk_off, acc):
        """acc += per-event count of famous round-i witnesses in this
        block that see the event (streaming elementwise, no gathers)."""
        sw = _col_gather(seqw_i, blk_off)
        fm = _col_gather(fam_i, blk_off, fill=False)
        sees = fm[None, :] & (fd_blk <= sw[None, :])         # [E+1, w]
        return acc + sees.sum(axis=1, dtype=I32)

    sees_partial_block = jax.jit(_sees_partial_block, donate_argnums=(4,))

    def _order_rr_update(state, und, decided_i, has_w_i, fam_cnt_i, i,
                         c, rr):
        i_abs = i + state.r_off
        active = decided_i & has_w_i & (i_abs <= state.max_round)
        cond = (
            und & (rr == -1) & (i_abs > state.round) & active
            & (c > fam_cnt_i // 2)
        )
        return jnp.where(cond, i_abs, rr)

    order_rr_update = jax.jit(_order_rr_update)

    med_chunk = max(1, min(order_ops.MEDIAN_CHUNK_ELEMS // n,
                           cfg.e_cap + 1))

    def _col_gather_t(tab, blk_off, fill=None):
        """Block-columns of an [R, n] table (clipped gather, see
        _col_gather)."""
        cols = blk_off + jnp.arange(w)
        out = tab[:, jnp.clip(cols, 0, tab.shape[1] - 1)]
        if fill is not None:
            out = jnp.where(cols[None, :] < n, out, fill)
        return out

    def _ts_range(state):
        valid = state.seq >= 0
        tmin = jnp.min(jnp.where(valid, state.ts, INT64_MAX))
        tmax = jnp.max(jnp.where(valid, state.ts, -INT64_MAX - 1))
        # real-world timestamps are granular (the sim quantizes to 1 us);
        # dividing by the granularity is what brings a multi-hour span
        # under 2^31 for the i32 median path
        div1000 = jnp.all(
            jnp.where(valid, (state.ts - tmin) % 1000, 0) == 0
        )
        return tmin, tmax, div1000

    ts_range = jax.jit(_ts_range)

    def _med_tv_block(state, fd_blk_rows, i_rows, seqw, fam, blk_off,
                      tmin, scale, rel32):
        """Per-block tv columns for a chunk of events: the timestamp of
        chain j's event at seq fd[x, j], masked to famous seers.

        ``rel32`` (static): timestamps span < 2^31 ns, so the median
        machinery runs on i32 offsets from tmin — the S-step
        select-accumulate and the sort are this phase's HBM-bound bulk
        (measured 62% of peak bandwidth at 10k x 600k), and halving the
        element width halves it.  Rows with no seers surface INF and are
        masked by `newly` downstream (a received event always has
        seers)."""
        rows_c = jnp.clip(blk_off + jnp.arange(w), 0, n)
        cej = state.ce[rows_c]                               # [w, S+1]
        ts_grid = state.ts[sanitize(cej, e_cap)]             # i64[w, S+1]
        inf = jnp.asarray(
            jnp.iinfo(jnp.int32).max if rel32 else INT64_MAX,
            jnp.int32 if rel32 else state.ts.dtype,
        )
        if rel32:
            # invalid grid cells wrap to garbage, but every cell a `sees`
            # row selects is a real event (fd <= seqw implies existence)
            ts_grid = ((ts_grid - tmin) // scale).astype(jnp.int32)
        sw = _col_gather_t(seqw, blk_off)[i_rows]            # [chunk, w]
        fm = _col_gather_t(fam, blk_off, fill=False)[i_rows]
        sees = fm & (fd_blk_rows <= sw)
        off = _col_gather(state.s_off, blk_off)
        fdc = jnp.clip(fd_blk_rows - off[None, :], 0, s_cap)
        if jax.default_backend() == "tpu" and s_cap < 2048:
            def acc_step(s, acc):
                return jnp.where(fdc == s, ts_grid[:, s][None, :], acc)

            tv = jax.lax.fori_loop(
                0, s_cap + 1, acc_step,
                jnp.full(fdc.shape, inf, dtype=ts_grid.dtype),
            )
        else:
            tv = ts_grid[jnp.arange(w)[None, :], fdc]
        return jnp.where(sees, tv, inf), sees.sum(axis=1, dtype=I32)

    med_tv_block = jax.jit(_med_tv_block, static_argnums=(8,))

    def _med_reduce(tv_full, cnt_s, newly_rows, cts_rows, tmin, scale,
                    rel32):
        tv_sorted = jnp.sort(tv_full, axis=1)
        rows = tv_full.shape[0]
        med = tv_sorted[jnp.arange(rows),
                        jnp.clip(cnt_s // 2, 0, n - 1)]
        if rel32:
            med = med.astype(jnp.int64) * scale + tmin
        return jnp.where(newly_rows, med, cts_rows)

    med_reduce = jax.jit(_med_reduce, static_argnums=(6,))

    def _slice_rows(a, e0, rows):
        return jax.lax.dynamic_slice_in_dim(a, e0, rows, 0)

    slice_rows = jax.jit(_slice_rows, static_argnums=(2,))

    def _write_rows(a, e0, rows):
        return jax.lax.dynamic_update_slice_in_dim(a, rows, e0, 0)

    write_rows = jax.jit(_write_rows)

    return dict(
        write_batch=write_batch, la_block_scan=la_block_scan,
        fd_block_scan=fd_block_scan, coord_sent=coord_sent,
        ss_partial=ss_partial, gather_rows=gather_rows,
        frontier_prep=frontier_prep, round_witnesses=round_witnesses,
        bisect_candidates=bisect_candidates, bisect_update=bisect_update,
        inherit_block=inherit_block, frontier_next=frontier_next,
        frontier_fin=frontier_fin,
        fame_wits=fame_wits, votes0_block=votes0_block,
        fame_tally=fame_tally, fame_write=fame_write, fame_fin=fame_fin,
        order_prep=order_prep, sees_partial_block=sees_partial_block,
        order_rr_update=order_rr_update, med_tv_block=med_tv_block,
        ts_range=ts_range,
        med_reduce=med_reduce, slice_rows=slice_rows,
        write_rows=write_rows, med_chunk=med_chunk, width=w,
    )


def _assert_fresh(state: DagState) -> None:
    """The wide pipeline is batch-only: it uses window-local seq
    invariants (one-hot strongly-see, block offsets) and indexes witness
    rows by absolute round, so rolled-window states are out of contract
    (the live engine drives the fused kernels with batch_window=False)."""
    if int(state.r_off) != 0:
        raise ValueError(
            "wide pipeline requires a fresh (un-compacted) state; "
            f"got r_off={int(state.r_off)}"
        )


def _init_blocks(cfg: DagConfig, C: int):
    w = _block_width(cfg, C)
    e1 = cfg.e_cap + 1
    la = tuple(jnp.full((e1, w), -1, cfg.coord_dtype) for _ in range(C))
    fd = tuple(
        jnp.full((e1, w), cfg.fd_inf, cfg.coord_dtype) for _ in range(C)
    )
    return la, fd


def _split_blocks(cfg: DagConfig, C: int, full: jnp.ndarray, fill):
    """Split a full [E+1, N] tensor into C padded column blocks."""
    w = _block_width(cfg, C)
    e1 = cfg.e_cap + 1
    out = []
    for c in range(C):
        blk = full[:, c * w : (c + 1) * w]
        if blk.shape[1] < w:
            blk = jnp.concatenate(
                [blk, jnp.full((e1, w - blk.shape[1]), fill, blk.dtype)],
                axis=1,
            )
        out.append(blk)
    return tuple(out)


def _assemble_blocks(cfg: DagConfig, blocks) -> jnp.ndarray:
    return jnp.concatenate(blocks, axis=1)[:, : cfg.n]


def run_wide_coords(cfg: DagConfig, state: DagState, batch: EventBatch,
                    la_blocks, fd_blocks, C: int):
    """Blocked coordinate fill: batch write + per-block la/fd scans."""
    j = _jits(cfg, C)
    state = j["write_batch"](state, batch)
    base = state.n_events - batch.k
    slot_sched = jnp.where(
        batch.sched >= 0, base + batch.sched, cfg.e_cap
    )
    w = j["width"]
    sp, op, creator, seq = state.sp, state.op, state.creator, state.seq
    la_blocks = tuple(
        j["la_block_scan"](sp, op, creator, seq, la_blocks[c],
                           slot_sched, jnp.asarray(c * w, I32))
        for c in range(C)
    )
    fd_blocks = tuple(
        j["fd_block_scan"](sp, op, creator, seq, batch.seq, batch.k,
                           state.n_events, fd_blocks[c], slot_sched,
                           jnp.asarray(c * w, I32))
        for c in range(C)
    )
    state = j["coord_sent"](state)
    return state, la_blocks, fd_blocks


def _blocked_ss(j, C, w, la_rows_by_block, fd_rows_by_block, n):
    """Accumulate per-block strongly-see partials into [A, B] counts."""
    acc = jnp.zeros(
        (la_rows_by_block[0].shape[0], fd_rows_by_block[0].shape[0]), I32
    )
    for c in range(C):
        acc = j["ss_partial"](la_rows_by_block[c], fd_rows_by_block[c],
                              acc)
    return acc


def run_wide_rounds(cfg: DagConfig, state: DagState, la_blocks,
                    fd_blocks, C: int, stats=None) -> DagState:
    """Blocked host-driven frontier march (device twin:
    _rounds_frontier, differentially tested)."""
    _assert_fresh(state)
    j = _jits(cfg, C)
    w = j["width"]
    n, s_cap, r_cap = cfg.n, cfg.s_cap, cfg.r_cap
    bisect_iters = max(1, (s_cap + 1).bit_length())

    cnt, pos, pos_table = j["frontier_prep"](state)
    r = 0
    alive = True
    while alive and r < r_cap - 1:
        ws, valid_w = j["round_witnesses"](state, cnt, pos)
        fdw = [j["gather_rows"](fd_blocks[c], ws) for c in range(C)]

        lo = jnp.where(valid_w, pos, cnt)
        hi = cnt
        for _ in range(bisect_iters):
            mid, xs = j["bisect_candidates"](state, lo, hi)
            law = [j["gather_rows"](la_blocks[c], xs) for c in range(C)]
            cnt_ab = _blocked_ss(j, C, w, law, fdw, n)
            lo, hi = j["bisect_update"](cnt_ab, valid_w, lo, hi, mid,
                                        cnt)
        s_star = lo
        found = s_star < cnt

        # descent inheritance via the first-inc events' fd rows
        _, e_star = j["bisect_candidates"](state, s_star, s_star)
        e_star = jnp.where(found, e_star, -1)
        inh = [
            j["inherit_block"](
                j["gather_rows"](fd_blocks[c], e_star),
                jnp.asarray(c * w, I32), state.s_off,
            )
            for c in range(C)
        ]
        inherit = jnp.concatenate(inh)[:n]
        pos, pos_table, any_next = j["frontier_next"](
            cnt, pos, pos_table, jnp.asarray(r, I32), s_star, found,
            inherit,
        )
        alive = bool(any_next)
        r += 1

    if stats is not None:
        stats["round_steps"] = r
        stats["bisect_iters"] = bisect_iters
    return j["frontier_fin"](state, pos_table)


def run_wide_fame(cfg: DagConfig, state: DagState, la_blocks, fd_blocks,
                  C: int, stats=None) -> DagState:
    """Blocked host-driven fame voting (device twin:
    decide_fame_block_impl, differentially tested)."""
    _assert_fresh(state)
    j = _jits(cfg, C)
    w = j["width"]
    n = cfg.n
    lcr = int(state.lcr)
    max_round = int(state.max_round)
    famous = state.famous
    for i_abs in range(max(lcr + 1, 0), max_round):
        i = i_abs  # r_off == 0 asserted
        ws_i, valid_i = j["fame_wits"](state, jnp.asarray(i, I32))
        seqw_i = state.seq[sanitize(ws_i, cfg.e_cap)]
        famous_i = famous[i]

        ws_1, valid_1 = j["fame_wits"](state, jnp.asarray(i + 1, I32))
        votes = jnp.concatenate(
            [
                j["votes0_block"](
                    j["gather_rows"](la_blocks[c], ws_1), seqw_i,
                    jnp.asarray(c * w, I32), valid_1, valid_i,
                )
                for c in range(C)
            ],
            axis=1,
        )[:, :n]

        und_any = bool(((np.asarray(famous_i) == fame_ops.FAME_UNDEFINED)
                        & np.asarray(valid_i)).any())
        d = 2
        while und_any and i_abs + d <= max_round:
            ws_j, valid_j = j["fame_wits"](state,
                                           jnp.asarray(i + d, I32))
            ws_p, valid_p = j["fame_wits"](state,
                                           jnp.asarray(i + d - 1, I32))
            law = [j["gather_rows"](la_blocks[c], ws_j)
                   for c in range(C)]
            fdw = [j["gather_rows"](fd_blocks[c], ws_p)
                   for c in range(C)]
            cnt_ab = _blocked_ss(j, C, w, law, fdw, n)
            mb_j = state.mbit[sanitize(ws_j, cfg.e_cap)]
            votes, famous_i, und = j["fame_tally"](
                cnt_ab, valid_j, valid_p, valid_i, votes, famous_i,
                mb_j, jnp.asarray(d, I32),
            )
            und_any = bool(und)
            d += 1
        if stats is not None:
            # rounds-to-fame latency: the voting distance at which round
            # i's witnesses were all decided (BASELINE's north-star
            # metric); max_round+1 marks "ran out of voting rounds"
            stats.setdefault("fame_decision_distance", {})[i_abs] = (
                d - 1 if not und_any else None
            )
            stats["fame_vote_steps"] = stats.get("fame_vote_steps", 0) \
                + (d - 2)
        famous = j["fame_write"](famous, famous_i, jnp.asarray(i, I32))
    state = state._replace(famous=famous)
    return state._replace(lcr=j["fame_fin"](state, famous))


def run_wide_order(cfg: DagConfig, state: DagState, la_blocks, fd_blocks,
                   C: int, stats=None) -> DagState:
    """Blocked host-driven round-received + median timestamps (device
    twin: decide_order_impl, differentially tested)."""
    _assert_fresh(state)
    j = _jits(cfg, C)
    w = j["width"]
    n, e1 = cfg.n, cfg.e_cap + 1
    seqw, fam, decided, has_w, fam_cnt, und = j["order_prep"](state)

    rr = state.rr
    for i in range(cfg.r_cap):
        c = jnp.zeros((e1,), I32)
        for blk in range(C):
            c = j["sees_partial_block"](
                fd_blocks[blk], seqw[i], fam[i],
                jnp.asarray(blk * w, I32), c,
            )
        rr = j["order_rr_update"](state, und, decided[i], has_w[i],
                                  fam_cnt[i], jnp.asarray(i, I32), c, rr)
    newly = und & (rr != -1)
    i_of = jnp.clip(rr - state.r_off, 0, cfg.r_cap - 1)

    tmin, tmax, div1000 = j["ts_range"](state)
    span = int(np.asarray(tmax - tmin))
    scale = 1000 if (bool(np.asarray(div1000))
                     and span // 1000 < (1 << 31) - 1
                     and span >= (1 << 31) - 1) else 1
    rel32 = span // scale < (1 << 31) - 1
    scale_j = jnp.asarray(scale, jnp.int64)
    cts = state.cts
    chunk = j["med_chunk"]
    for k, e0 in enumerate(range(0, e1, chunk)):
        e0 = min(e0, e1 - chunk) if e1 >= chunk else 0
        e0j = jnp.asarray(e0, I32)
        i_rows = j["slice_rows"](i_of, e0j, chunk)
        tvs, cnts = [], []
        for blk in range(C):
            fd_rows = j["slice_rows"](fd_blocks[blk], e0j, chunk)
            tv_b, cnt_b = j["med_tv_block"](
                state, fd_rows, i_rows, seqw, fam,
                jnp.asarray(blk * w, I32), tmin, scale_j, rel32,
            )
            tvs.append(tv_b)
            cnts.append(cnt_b)
        tv_full = jnp.concatenate(tvs, axis=1)[:, :n]
        cnt_s = sum(cnts[1:], cnts[0])
        new_rows = j["slice_rows"](newly, e0j, chunk)
        cts_rows = j["slice_rows"](cts, e0j, chunk)
        upd = j["med_reduce"](tv_full, cnt_s, new_rows, cts_rows, tmin,
                              scale_j, rel32)
        cts = j["write_rows"](cts, e0j, upd)
        if k % 8 == 7:
            _ = np.asarray(cts[:1])      # dispatch backpressure
    if stats is not None:
        stats["median_chunks"] = -(-e1 // chunk)
        stats["median_chunk_rows"] = chunk
        stats["median_rel32"] = rel32
    return state._replace(rr=rr, cts=cts)


def run_wide_pipeline(
    cfg: DagConfig,
    batch: EventBatch,
    state: Optional[DagState] = None,
    fd_mode: str = "fast",
    timings: Optional[dict] = None,
    n_blocks: Optional[int] = None,
    assemble: bool = True,
    stats: Optional[dict] = None,
) -> DagState:
    """Full batch pipeline at wide N: coords -> rounds -> fame -> order.

    ``timings``, if given, receives per-phase wall seconds (the hook the
    bench's MFU accounting uses).  ``assemble=False`` skips rebuilding
    the full [E+1, N] la/fd from their blocks (they would not fit next
    to the blocks at the 10k-deep configs); the returned state then has
    la/fd = None and only consensus-observable fields are meaningful.
    """
    import time

    if fd_mode != "fast":
        raise ValueError("wide pipeline supports the 'fast' batch mode")
    C = n_blocks or block_count(cfg)
    if stats is not None:
        stats["n_blocks"] = C
        stats["onehot_partials"] = _use_onehot_partial(cfg)
        stats["levels"] = int(batch.sched.shape[0])

    def tick(name, t0):
        if timings is not None:
            timings[name] = timings.get(name, 0.0) + time.perf_counter() - t0

    if state is None:
        state = init_state(cfg, include_coords=False)
    _assert_fresh(state)
    # discard the fused-layout coordinate tensors: the wide path owns
    # its blocked twins (split is only needed when resuming mid-state,
    # which the batch pipeline never does — state is fresh)
    la_full, fd_full = state.la, state.fd
    if la_full is not None and int(state.n_events) > 0:
        la_blocks = _split_blocks(cfg, C, la_full, -1)
        fd_blocks = _split_blocks(cfg, C, fd_full, cfg.fd_inf)
    else:
        la_blocks, fd_blocks = _init_blocks(cfg, C)
    state = state._replace(la=None, fd=None)
    del la_full, fd_full
    jax.block_until_ready(state)

    t0 = time.perf_counter()
    state, la_blocks, fd_blocks = run_wide_coords(
        cfg, state, batch, la_blocks, fd_blocks, C
    )
    _ = np.asarray(state.n_events)    # hard sync for honest phase timing
    jax.block_until_ready(la_blocks + fd_blocks)
    _ = np.asarray(la_blocks[0][:1, :1])
    tick("coords", t0)
    t0 = time.perf_counter()
    state = run_wide_rounds(cfg, state, la_blocks, fd_blocks, C, stats)
    _ = np.asarray(state.max_round)
    tick("rounds", t0)
    t0 = time.perf_counter()
    state = run_wide_fame(cfg, state, la_blocks, fd_blocks, C, stats)
    _ = np.asarray(state.lcr)
    tick("fame", t0)
    t0 = time.perf_counter()
    state = run_wide_order(cfg, state, la_blocks, fd_blocks, C, stats)
    _ = np.asarray(state.rr[:1])
    tick("order", t0)
    if assemble:
        state = state._replace(
            la=_assemble_blocks(cfg, la_blocks),
            fd=_assemble_blocks(cfg, fd_blocks),
        )
    return state

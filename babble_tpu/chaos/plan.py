"""Declarative fault plans: what the chaos plane may do to the network.

A :class:`FaultPlan` is pure data — per-link fault probabilities,
scheduled partitions with heal times, node crash/restart points, and an
optional byzantine actor — with a stable JSON form (see README "Chaos
testing" for the schema).  The plan never draws randomness itself: the
:class:`~babble_tpu.chaos.injector.FaultInjector` turns a (plan, seed)
pair into concrete fault decisions, which is what makes every scenario
reproducible from ``--seed`` alone.

Time is measured in abstract **ticks**: the deterministic scenario
runner advances one tick per gossip step, the live runner maps ticks to
wall time through ``Scenario.tick_seconds``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: invariants the checker knows how to enforce (invariants.py)
KNOWN_INVARIANTS = (
    "prefix_agreement",   # safety: honest nodes commit identical order
    "liveness",           # commits resume within a bound after heal
    "all_committed",      # every submitted tx reaches the honest logs
    "fork_detected",      # every honest node flagged the equivocation
    "fast_forwarded",     # a restarted node caught up via snapshot
    "eviction_advanced",  # a silent creator's tail evicted; memory bounded
    "ff_proof_rejected",  # a forged snapshot was refused (proof quorum)
    "epoch_agreement",    # every honest node applied every membership
                          # transition at the same decided round
    "skew_robust_order",  # committed order identical to the same run
                          # with clock drift off / timestamp lying off
                          # (cts median robustness)
)

BYZANTINE_MODES = ("fork", "stale_replay", "forge_snapshot", "lying_ts")


def _prob(v, name: str) -> float:
    f = float(v)
    if not 0.0 <= f <= 1.0:
        raise ValueError(f"{name} must be a probability in [0, 1], got {v}")
    return f


def _ms_range(v, name: str) -> Tuple[float, float]:
    lo, hi = (float(v[0]), float(v[1]))
    if lo < 0 or hi < lo:
        raise ValueError(f"{name} must be 0 <= lo <= hi ms, got {v}")
    return (lo, hi)


@dataclass(frozen=True)
class LinkFaults:
    """Per-directed-link fault probabilities plus WAN-shaped link
    models (ROADMAP items 3+5).  ``delay``/``reorder`` are
    probabilities; the matching ``*_ms`` ranges bound the injected
    latency (reordering is modeled as extra delay on the affected
    message relative to the messages behind it).

    WAN models (both off by default, so pre-existing plans — and their
    per-link RNG streams — are untouched):

    - **bandwidth**: ``bw_kbps`` (kilobits/s; 0 = unlimited) applies a
      token-bucket cap with ``bw_burst_kb`` of burst: every
      gossip-class message pays a size-proportional serialization
      delay, and messages past the bucket queue behind the deficit.
      Draws no randomness — the schedule is a pure function of the
      (deterministic) message sizes and ticks.
    - **Gilbert–Elliott burst loss**: a two-state good/bad loss chain
      (``ge_p_gb``/``ge_p_bg`` transition probabilities per message,
      ``ge_drop_good``/``ge_drop_bad`` loss rates per state), drawn
      from the same per-link seeded RNG stream as the classic faults —
      loss arrives in bursts, the shape one lossy WAN hop actually has.
    """

    drop: float = 0.0
    delay: float = 0.0
    delay_ms: Tuple[float, float] = (1.0, 5.0)
    duplicate: float = 0.0
    reorder: float = 0.0
    reorder_ms: Tuple[float, float] = (1.0, 10.0)
    bw_kbps: float = 0.0
    bw_burst_kb: float = 64.0
    ge_p_gb: float = 0.0
    ge_p_bg: float = 0.0
    ge_drop_good: float = 0.0
    ge_drop_bad: float = 1.0

    def __post_init__(self):
        _prob(self.drop, "drop")
        _prob(self.delay, "delay")
        _prob(self.duplicate, "duplicate")
        _prob(self.reorder, "reorder")
        _prob(self.ge_p_gb, "ge_p_gb")
        _prob(self.ge_p_bg, "ge_p_bg")
        _prob(self.ge_drop_good, "ge_drop_good")
        _prob(self.ge_drop_bad, "ge_drop_bad")
        if self.bw_kbps < 0:
            raise ValueError(f"bw_kbps must be >= 0, got {self.bw_kbps}")
        if self.bw_burst_kb <= 0:
            raise ValueError(
                f"bw_burst_kb must be positive, got {self.bw_burst_kb}"
            )
        object.__setattr__(self, "delay_ms",
                           _ms_range(self.delay_ms, "delay_ms"))
        object.__setattr__(self, "reorder_ms",
                           _ms_range(self.reorder_ms, "reorder_ms"))

    @property
    def ge_enabled(self) -> bool:
        return self.ge_p_gb > 0

    def to_dict(self) -> dict:
        out = {
            "drop": self.drop, "delay": self.delay,
            "delay_ms": list(self.delay_ms),
            "duplicate": self.duplicate, "reorder": self.reorder,
            "reorder_ms": list(self.reorder_ms),
        }
        # WAN keys ride only when set: pre-WAN plan JSON stays stable
        if self.bw_kbps:
            out["bw_kbps"] = self.bw_kbps
            out["bw_burst_kb"] = self.bw_burst_kb
        if self.ge_enabled:
            out["ge_p_gb"] = self.ge_p_gb
            out["ge_p_bg"] = self.ge_p_bg
            out["ge_drop_good"] = self.ge_drop_good
            out["ge_drop_bad"] = self.ge_drop_bad
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "LinkFaults":
        known = {"drop", "delay", "delay_ms", "duplicate", "reorder",
                 "reorder_ms", "bw_kbps", "bw_burst_kb", "ge_p_gb",
                 "ge_p_bg", "ge_drop_good", "ge_drop_bad"}
        extra = set(d) - known
        if extra:
            raise ValueError(f"unknown link fault keys: {sorted(extra)}")
        kw = dict(d)
        for k in ("delay_ms", "reorder_ms"):
            if k in kw:
                kw[k] = tuple(kw[k])
        return cls(**kw)


@dataclass(frozen=True)
class LinkOverride:
    """Override the default link faults for links matching (src, dst);
    ``None`` matches any node — ``src=2, dst=None`` degrades every link
    *out of* node 2 (the slow-peer shape)."""

    faults: LinkFaults
    src: Optional[int] = None
    dst: Optional[int] = None

    def matches(self, src: int, dst: int) -> bool:
        return ((self.src is None or self.src == src)
                and (self.dst is None or self.dst == dst))


@dataclass(frozen=True)
class Partition:
    """From tick ``start`` until ``heal`` (exclusive; ``None`` = never),
    the listed group cannot exchange messages with everyone else in
    either direction."""

    group: Tuple[int, ...]
    start: int
    heal: Optional[int] = None

    def __post_init__(self):
        if self.heal is not None and self.heal <= self.start:
            raise ValueError(
                f"partition heal {self.heal} must be after start {self.start}"
            )
        object.__setattr__(self, "group", tuple(self.group))

    def active(self, tick: float) -> bool:
        return tick >= self.start and (self.heal is None or tick < self.heal)

    def separates(self, src: int, dst: int, tick: float) -> bool:
        if not self.active(tick):
            return False
        return (src in self.group) != (dst in self.group)


@dataclass(frozen=True)
class Crash:
    """Node ``node`` goes down at tick ``crash``; ``restart=None``
    means it stays down."""

    node: int
    crash: int
    restart: Optional[int] = None

    def __post_init__(self):
        if self.restart is not None and self.restart <= self.crash:
            raise ValueError(
                f"restart {self.restart} must be after crash {self.crash}"
            )


@dataclass(frozen=True)
class MembershipOp:
    """One scheduled churn verb (membership plane).  ``join``: node
    ``node`` (an index at or past the founding set — the runner boots
    it as an observer at this tick) submits its signed join tx through
    node ``via``'s pool.  ``leave``: founding-or-joined node ``node``
    announces departure — the tx is signed by the SUBJECT's key but may
    be submitted via any live node, which is what makes
    leave-mid-outage possible (the runner holds every scenario key)."""

    kind: str            # "join" | "leave"
    tick: int
    node: int
    via: Optional[int] = None

    def __post_init__(self):
        if self.kind not in ("join", "leave"):
            raise ValueError(f"unknown membership kind {self.kind!r}")
        if self.tick < 0:
            raise ValueError("membership tick must be >= 0")


@dataclass(frozen=True)
class ClockSkew:
    """Per-node bounded clock drift (ROADMAP item 5, first slice):
    every affected node's ``Core.now_ns`` is offset by a constant drawn
    from the injector's seeded per-node stream, uniform in
    ``[-max_ms, +max_ms]``.  ``nodes=None`` drifts everyone.  The
    ``skew_robust_order`` invariant asserts the committed order is
    IDENTICAL to the drift-free twin run — median timestamps absorb
    bounded per-creator skew."""

    max_ms: float = 0.5
    nodes: Optional[Tuple[int, ...]] = None

    def __post_init__(self):
        if self.max_ms < 0:
            raise ValueError("clock skew max_ms must be >= 0")
        if self.nodes is not None:
            object.__setattr__(self, "nodes", tuple(self.nodes))

    def affects(self, node: int) -> bool:
        return self.nodes is None or node in self.nodes


#: disk-fault kinds, in the order the injector draws them at restart
DISK_FAULT_KINDS = (
    "checkpoint_corrupt", "checkpoint_truncate",
    "wal_corrupt", "wal_truncate",
)


@dataclass(frozen=True)
class DiskFaults:
    """Durable-state rot applied to a node's checkpoint/WAL files at
    *restart* time (a crash is when fsync lies surface): each field is
    the probability that kind fires on a given restart, drawn from the
    injector's per-node seeded disk stream.  Corrupt = flip one byte at
    a seeded offset; truncate = chop a seeded number of tail bytes.
    The restarted node must recover through the durability ladder
    (checkpoint -> WAL replay truncated at the damage -> seq probe ->
    gossip/fast-forward) without ever violating prefix agreement."""

    checkpoint_corrupt: float = 0.0
    checkpoint_truncate: float = 0.0
    wal_corrupt: float = 0.0
    wal_truncate: float = 0.0

    def __post_init__(self):
        for kind in DISK_FAULT_KINDS:
            _prob(getattr(self, kind), kind)

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in DISK_FAULT_KINDS}

    @classmethod
    def from_dict(cls, d: dict) -> "DiskFaults":
        extra = set(d) - set(DISK_FAULT_KINDS)
        if extra:
            raise ValueError(f"unknown disk fault keys: {sorted(extra)}")
        return cls(**d)


@dataclass(frozen=True)
class ByzantineSpec:
    """One byzantine actor.  ``fork`` mints an equivocating event at
    tick ``at`` and plants the branches at two different peers;
    ``stale_replay`` answers inbound syncs with a cached stale response
    with probability ``prob`` from tick ``at`` on; ``forge_snapshot``
    answers every fast-forward request from tick ``at`` on with a
    DOCTORED snapshot — committed history rewritten, digest recomputed
    self-consistently, proof re-signed under the actor's own key — the
    protocol-aware-recovery attack verified fast-forward exists to
    refuse; ``lying_ts`` mints events whose claimed timestamps are
    EXTREME lies (each mint lies with probability ``prob`` from tick
    ``at`` on, offsets drawn from a dedicated seeded stream) — the
    creator-claimed-median ordering attack the per-creator timestamp
    clamp (core/dag.py TS_CLAMP_WINDOW_NS) exists to absorb."""

    node: int
    mode: str = "fork"
    at: int = 0
    prob: float = 0.3

    def __post_init__(self):
        if self.mode not in BYZANTINE_MODES:
            raise ValueError(
                f"byzantine mode {self.mode!r} not in {BYZANTINE_MODES}"
            )
        _prob(self.prob, "byzantine prob")


@dataclass
class FaultPlan:
    """The full declarative fault surface for one scenario."""

    default: LinkFaults = field(default_factory=LinkFaults)
    overrides: List[LinkOverride] = field(default_factory=list)
    partitions: List[Partition] = field(default_factory=list)
    crashes: List[Crash] = field(default_factory=list)
    byzantine: Optional[ByzantineSpec] = None
    #: durable-state rot applied at restart time (None = disks behave)
    disk: Optional[DiskFaults] = None
    #: membership churn verbs (membership plane): scheduled join/leave
    #: transitions submitted as signed txs through the ordinary ingress
    joins: List[MembershipOp] = field(default_factory=list)
    leaves: List[MembershipOp] = field(default_factory=list)
    #: per-node bounded clock drift (None = clocks honest)
    clock_skew: Optional[ClockSkew] = None

    def link(self, src: int, dst: int) -> LinkFaults:
        """Resolved faults for the directed link src -> dst (last
        matching override wins; most-specific plans list specific
        overrides last)."""
        out = self.default
        for ov in self.overrides:
            if ov.matches(src, dst):
                out = ov.faults
        return out

    def partitioned(self, src: int, dst: int, tick: float) -> bool:
        return any(p.separates(src, dst, tick) for p in self.partitions)

    def validate(self, n_nodes: int, joiners: int = 0) -> None:
        total = n_nodes + joiners

        def _node(i, what, bound=n_nodes):
            if not 0 <= i < bound:
                raise ValueError(
                    f"{what} node {i} out of range 0..{bound - 1}"
                )

        for ov in self.overrides:
            for v, what in ((ov.src, "override src"), (ov.dst, "override dst")):
                if v is not None:
                    _node(v, what, total)
        for p in self.partitions:
            for i in p.group:
                _node(i, "partition", total)
            if len(p.group) >= total:
                raise ValueError("partition group must leave someone outside")
        for c in self.crashes:
            _node(c.node, "crash", total)
        if self.byzantine is not None:
            _node(self.byzantine.node, "byzantine")
        if len(self.joins) != joiners:
            raise ValueError(
                f"plan schedules {len(self.joins)} joins but the "
                f"scenario declares {joiners} joiners"
            )
        for j, op in enumerate(self.joins):
            if op.kind != "join":
                raise ValueError("joins list carries a non-join op")
            if op.node != n_nodes + j:
                raise ValueError(
                    f"join #{j} must target node {n_nodes + j} (joiner "
                    f"indices follow the founding set in schedule order)"
                )
            if op.via is not None:
                _node(op.via, "join via")
        for op in self.leaves:
            if op.kind != "leave":
                raise ValueError("leaves list carries a non-leave op")
            _node(op.node, "leave", total)
            if op.via is not None:
                _node(op.via, "leave via", total)

    def to_dict(self) -> dict:
        out: dict = {"default": self.default.to_dict()}
        if self.overrides:
            out["overrides"] = [
                {"src": ov.src, "dst": ov.dst, **ov.faults.to_dict()}
                for ov in self.overrides
            ]
        if self.partitions:
            out["partitions"] = [
                {"group": list(p.group), "start": p.start, "heal": p.heal}
                for p in self.partitions
            ]
        if self.crashes:
            out["crashes"] = [
                {"node": c.node, "crash": c.crash, "restart": c.restart}
                for c in self.crashes
            ]
        if self.byzantine is not None:
            b = self.byzantine
            out["byzantine"] = {"node": b.node, "mode": b.mode,
                                "at": b.at, "prob": b.prob}
        if self.disk is not None:
            out["disk"] = self.disk.to_dict()
        if self.joins:
            out["joins"] = [
                {"tick": op.tick, "node": op.node, "via": op.via}
                for op in self.joins
            ]
        if self.leaves:
            out["leaves"] = [
                {"tick": op.tick, "node": op.node, "via": op.via}
                for op in self.leaves
            ]
        if self.clock_skew is not None:
            out["clock_skew"] = {
                "max_ms": self.clock_skew.max_ms,
                "nodes": (list(self.clock_skew.nodes)
                          if self.clock_skew.nodes is not None else None),
            }
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        known = {"default", "overrides", "partitions", "crashes",
                 "byzantine", "disk", "joins", "leaves", "clock_skew"}
        extra = set(d) - known
        if extra:
            raise ValueError(f"unknown fault plan keys: {sorted(extra)}")
        overrides = []
        for ov in d.get("overrides", []):
            ov = dict(ov)
            src, dst = ov.pop("src", None), ov.pop("dst", None)
            overrides.append(LinkOverride(
                faults=LinkFaults.from_dict(ov), src=src, dst=dst,
            ))
        byz = d.get("byzantine")
        disk = d.get("disk")
        skew = d.get("clock_skew")
        return cls(
            default=LinkFaults.from_dict(d.get("default", {})),
            overrides=overrides,
            partitions=[Partition(**p) for p in d.get("partitions", [])],
            crashes=[Crash(**c) for c in d.get("crashes", [])],
            byzantine=ByzantineSpec(**byz) if byz else None,
            disk=DiskFaults.from_dict(disk) if disk else None,
            joins=[MembershipOp(kind="join", **j)
                   for j in d.get("joins", [])],
            leaves=[MembershipOp(kind="leave", **lv)
                    for lv in d.get("leaves", [])],
            clock_skew=ClockSkew(**skew) if skew else None,
        )


@dataclass
class Scenario:
    """A fault plan plus the cluster + workload it runs against and the
    invariants the result must satisfy."""

    name: str
    nodes: int = 4
    steps: int = 240
    seed: int = 7
    #: membership plane: nodes beyond the founding set that JOIN during
    #: the run (plan.joins schedules when; joiner i takes scenario index
    #: nodes + i).  Joiners boot as observers at their join tick.
    joiners: int = 0
    plan: FaultPlan = field(default_factory=FaultPlan)
    #: consensus engine the cluster runs: "fused" (honest) or
    #: "byzantine" (fork-aware).  A fork-attack scenario run with
    #: "fused" is the intentionally-broken demo — the attack's branches
    #: are rejected instead of detected, and the fork_detected
    #: invariant fails loudly.
    engine: str = "fused"
    cache_size: int = 512
    seq_window: Optional[int] = None
    #: per-creator eviction: decided rounds of silence after which a
    #: creator's retained tail evicts (None = node-config default; the
    #: dead-creator scenario sets it low so the outage crosses it)
    inactive_rounds: Optional[int] = None
    txs: int = 16
    tx_every: int = 5
    invariants: Tuple[str, ...] = ("prefix_agreement", "liveness")
    #: liveness bound: consensus must advance on every honest live node
    #: within this many ticks of the last heal/restart
    liveness_bound: int = 120
    #: fault-free all-to-all gossip rounds appended after the plan runs
    #: (the "network eventually behaves" phase convergence checks need)
    settle_rounds: int = 6
    #: in-memory runner: save a durable checkpoint for every live node
    #: each N ticks (0 = WAL-only durability).  Only meaningful when the
    #: plan crashes nodes — a stale checkpoint plus the WAL tail is
    #: exactly the state a restart must recover from.
    checkpoint_every: int = 0
    #: live mode: wall seconds per tick
    tick_seconds: float = 0.05

    def __post_init__(self):
        if self.nodes < 2:
            raise ValueError("a scenario needs at least 2 nodes")
        if self.steps <= 0:
            raise ValueError("steps must be positive")
        if self.engine not in ("fused", "byzantine"):
            raise ValueError(f"unknown engine {self.engine!r}")
        unknown = set(self.invariants) - set(KNOWN_INVARIANTS)
        if unknown:
            raise ValueError(
                f"unknown invariants {sorted(unknown)}; "
                f"known: {KNOWN_INVARIANTS}"
            )
        if self.joiners < 0:
            raise ValueError("joiners must be >= 0")
        if self.joiners and self.engine != "fused":
            raise ValueError(
                "membership churn requires the fused engine (epoch "
                "transitions are not implemented for wide/byzantine)"
            )
        object.__setattr__(self, "invariants", tuple(self.invariants))
        self.plan.validate(self.nodes, self.joiners)

    def to_dict(self) -> dict:
        return {
            "name": self.name, "nodes": self.nodes, "steps": self.steps,
            "seed": self.seed, "joiners": self.joiners,
            "engine": self.engine,
            "cache_size": self.cache_size, "seq_window": self.seq_window,
            "inactive_rounds": self.inactive_rounds,
            "txs": self.txs, "tx_every": self.tx_every,
            "invariants": list(self.invariants),
            "liveness_bound": self.liveness_bound,
            "settle_rounds": self.settle_rounds,
            "checkpoint_every": self.checkpoint_every,
            "tick_seconds": self.tick_seconds,
            "plan": self.plan.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Scenario":
        d = dict(d)
        plan = FaultPlan.from_dict(d.pop("plan", {}))
        known = {
            "name", "nodes", "steps", "seed", "joiners", "engine",
            "cache_size", "seq_window", "inactive_rounds", "txs",
            "tx_every", "invariants", "liveness_bound", "settle_rounds",
            "checkpoint_every", "tick_seconds",
        }
        extra = set(d) - known
        if extra:
            raise ValueError(f"unknown scenario keys: {sorted(extra)}")
        if "invariants" in d:
            d["invariants"] = tuple(d["invariants"])
        return cls(plan=plan, **d)

    @classmethod
    def from_json_file(cls, path: str) -> "Scenario":
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_dict(json.load(f))


#: per-tick schedule of crash/restart actions, derived once per run
def crash_schedule(plan: FaultPlan) -> Dict[int, List[Tuple[str, int]]]:
    """tick -> [("crash"|"restart", node)] in declaration order."""
    out: Dict[int, List[Tuple[str, int]]] = {}
    for c in plan.crashes:
        out.setdefault(c.crash, []).append(("crash", c.node))
        if c.restart is not None:
            out.setdefault(c.restart, []).append(("restart", c.node))
    return out

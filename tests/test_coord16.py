"""int16 coordinate tensors (DagConfig.coord16): bit-parity with int32.

la/fd are the dominant HBM residents; coord16 halves them, which is what
fits the deep 10k-participant configs on one 16 GB chip.  Every value is
a per-creator seq bounded by s_cap, so int16 is exact when
s_cap < 2^14 (coord16_ok) — these tests pin i16 == i32 across the fused
pipeline, the wide host-driven pipeline, every fd strategy, and the
checkpoint layout."""

import functools

import jax
import numpy as np
import pytest

from babble_tpu.ops.state import (
    DagConfig,
    assert_consensus_parity,
    coord16_ok,
    init_state,
)
from babble_tpu.ops.wide import run_wide_pipeline
from babble_tpu.parallel.sharded import consensus_step_impl
from babble_tpu.sim.arrays import batch_from_arrays, random_gossip_arrays


def _parity_fields_equal(a, b, e):
    for f in ("round", "witness", "rr", "cts"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f))[:e], np.asarray(getattr(b, f))[:e],
            err_msg=f,
        )
    np.testing.assert_array_equal(np.asarray(a.famous), np.asarray(b.famous))
    assert int(a.lcr) == int(b.lcr)
    # coordinates agree as integers (dtypes differ by design)
    np.testing.assert_array_equal(
        np.asarray(a.la)[:e].astype(np.int32), np.asarray(b.la)[:e]
    )
    fa = np.asarray(a.fd)[:e].astype(np.int64)
    fb = np.asarray(b.fd)[:e].astype(np.int64)
    inf_a = np.iinfo(np.asarray(a.fd).dtype).max
    inf_b = np.iinfo(np.asarray(b.fd).dtype).max
    np.testing.assert_array_equal(fa == inf_a, fb == inf_b)
    m = fa != inf_a
    np.testing.assert_array_equal(fa[m], fb[m])


@pytest.mark.parametrize("fd_mode", ["fast", "full", "incremental"])
@pytest.mark.parametrize("narrow", [dict(coord16=True), dict(coord8=True)])
def test_narrow_coord_fused_parity(fd_mode, narrow):
    n, e = 16, 500
    dag = random_gossip_arrays(n, e, seed=21)
    batch = batch_from_arrays(dag)
    base = dict(n=n, e_cap=e, s_cap=dag.max_chain + 2, r_cap=32)
    cfg32 = DagConfig(**base)
    cfg16 = DagConfig(**base, **narrow)
    assert coord16_ok(cfg16.s_cap)

    out32 = jax.jit(functools.partial(consensus_step_impl, cfg32, fd_mode))(
        init_state(cfg32), batch
    )
    out16 = jax.jit(functools.partial(consensus_step_impl, cfg16, fd_mode))(
        init_state(cfg16), batch
    )
    _parity_fields_equal(out16, out32, e)
    assert int(out32.lcr) >= 0


def test_coord16_wide_parity():
    n, e = 24, 1200
    dag = random_gossip_arrays(n, e, seed=22)
    batch = batch_from_arrays(dag)
    base = dict(n=n, e_cap=e, s_cap=dag.max_chain + 2, r_cap=32)
    cfg32 = DagConfig(**base)
    cfg16 = DagConfig(**base, coord16=True)
    out32 = jax.jit(functools.partial(consensus_step_impl, cfg32, "fast"))(
        init_state(cfg32), batch
    )
    out16 = run_wide_pipeline(cfg16, batch)
    _parity_fields_equal(out16, out32, e)


def test_coord16_engine_and_checkpoint(tmp_path):
    """Engine-level coord16 (incremental live path) + snapshot roundtrip."""
    from babble_tpu.consensus.engine import TpuHashgraph
    from babble_tpu.sim.generator import random_gossip_dag
    from babble_tpu.store.checkpoint import load_checkpoint, save_checkpoint

    dag = random_gossip_dag(7, 250, seed=5)
    engines = {}
    for c16 in (False, True):
        eng = TpuHashgraph(dag.participants, verify_signatures=False,
                           e_cap=512, s_cap=64, r_cap=32)
        if c16:
            eng.cfg = eng.cfg._replace(coord16=True)
            eng.state = init_state(eng.cfg)
        for ev in dag.events:
            eng.insert_event(ev)
        eng.run_consensus()
        engines[c16] = eng
    assert engines[True].consensus_events() == engines[False].consensus_events()
    assert len(engines[True].consensus_events()) > 30

    path = tmp_path / "snap.ckpt"
    save_checkpoint(engines[True], str(path))
    eng2 = load_checkpoint(str(path))
    assert eng2.cfg.coord16 is True
    assert eng2.consensus_events() == engines[True].consensus_events()


def test_coord8_overflow_guard_covers_pending_batch():
    """ADVICE r3: a pending batch that crosses the narrow-coordinate
    headroom must raise OverflowError at flush — before any device
    write could wrap int8 la/fd values.  Host chains include pending
    events (OffsetList length is absolute), so the guard's head count
    sees the whole batch."""
    from babble_tpu.consensus.engine import TpuHashgraph
    from babble_tpu.core.event import new_event
    from babble_tpu.crypto.keys import generate_key

    keys = sorted((generate_key() for _ in range(2)),
                  key=lambda k: k.pub_hex)
    participants = {k.pub_hex: i for i, k in enumerate(keys)}
    from babble_tpu.ops.state import init_state

    eng = TpuHashgraph(participants, verify_signatures=False,
                       e_cap=256, s_cap=110, r_cap=16)
    eng.cfg = eng.cfg._replace(coord8=True)
    eng.state = init_state(eng.cfg)

    heads = {}
    for i, k in enumerate(keys):
        ev = new_event([], ("", ""), k.pub_bytes, 0)
        ev.sign(k)
        eng.insert_event(ev)
        heads[i] = ev.hex()
    key0 = keys[0]
    seq = 0
    for q in range(1, 92):
        ev = new_event([], (heads[0], heads[1]), key0.pub_bytes, q)
        ev.sign(key0)
        eng.insert_event(ev)
        heads[0] = ev.hex()
        seq = q
    eng.flush()   # safe: head seq 91 below the int8 sentinel headroom

    for q in range(seq + 1, seq + 40):   # batch spans the 126 edge
        ev = new_event([], (heads[0], heads[1]), key0.pub_bytes, q)
        ev.sign(key0)
        eng.insert_event(ev)
        heads[0] = ev.hex()
    import pytest as _pytest

    with _pytest.raises(OverflowError):
        eng.flush()

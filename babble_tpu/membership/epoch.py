"""The epoch ledger: verifying a membership log against a trusted base.

A fast-forward snapshot from a later epoch carries a peer set the
joiner has never seen.  Snapshot trust deliberately does NOT extend to
membership (ADVICE r2: a fabricated validator set is self-consistent
under every later signature check) — instead the snapshot's
``membership_log`` is a chain of custody: each entry embeds the SIGNED
transition transaction that consensus ordered, so the joiner can
replay the suffix beyond its own epoch on top of the peer set it
already trusts (its bootstrap peers.json, or its current live set) and
check that the result is exactly the set the snapshot claims.  A
forged set would need forged subject signatures; a replayed stale
transition fails the per-entry epoch check.

The commit-digest attestation quorum (store/proof.py) then ties the
log to committed history: the transitions are IN the committed order
the quorum co-signs, so a snapshot cannot carry a membership log that
honest nodes never ordered.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .transition import parse_membership_tx

#: hard bound on how many transitions one verification will replay —
#: a hostile log must cost nothing to reject
MAX_LOG = 4096

#: pipelined membership (ROADMAP 5a): a transition may be stamped up to
#: this many epochs before the epoch it applies in (transitions queued
#: behind a pending boundary keep their submission-time stamp).  Must
#: equal consensus.engine.MEMBERSHIP_QUEUE_MAX — the engine never
#: queues deeper than this, so any wider gap in a log is a forgery.
#: Replay protection is unchanged in substance: the engine rejects
#: stamps below its CURRENT epoch at commit time, so a stale leave
#: still cannot re-remove a member who rejoined epochs ago.
PIPELINE_WINDOW = 64


def check_log_entry(entry: dict) -> Optional[str]:
    """Structural bounds for one serialized membership-log entry
    (checkpoint/snapshot hostile-input checking).  Returns an error
    string or None."""
    if not isinstance(entry, dict):
        return "membership log entry is not a map"
    for key, typ in (("epoch", int), ("kind", str), ("pub", str),
                     ("addr", str), ("boundary", int), ("position", int)):
        if not isinstance(entry.get(key), typ):
            return f"membership log entry field {key} malformed"
    if entry["kind"] not in ("join", "leave"):
        return f"membership log kind {entry['kind']!r} unknown"
    if not (0 < entry["epoch"] <= 1 << 32):
        return "membership log epoch out of bounds"
    if not (0 <= entry["boundary"] <= 1 << 32):
        return "membership log boundary out of bounds"
    if not (0 <= entry["position"] <= 1 << 48):
        return "membership log position out of bounds"
    tx = entry.get("tx")
    if not isinstance(tx, (bytes, bytearray)) or len(tx) > 4096:
        return "membership log tx malformed"
    return None


def replay_log(
    base_participants: Dict[str, int],
    base_retired: Tuple[int, ...],
    entries: List[dict],
    from_epoch: int,
) -> Tuple[Dict[str, int], Tuple[int, ...]]:
    """Replay the log suffix with epoch > ``from_epoch`` on top of the
    base set, verifying each embedded signed transition.  Returns the
    resulting (participants, retired).  Raises ValueError on any
    malformed, mis-signed or inconsistent entry."""
    if len(entries) > MAX_LOG:
        raise ValueError(f"membership log too long ({len(entries)})")
    participants = dict(base_participants)
    retired = tuple(base_retired)
    epoch = from_epoch
    for entry in entries:
        err = check_log_entry(entry)
        if err is not None:
            raise ValueError(err)
        if entry["epoch"] <= from_epoch:
            continue   # the trusted base already includes this epoch
        if entry["epoch"] != epoch + 1:
            raise ValueError(
                f"membership log skips from epoch {epoch} to "
                f"{entry['epoch']}"
            )
        tx = parse_membership_tx(bytes(entry["tx"]))
        if tx is None:
            raise ValueError("membership log carries an unparseable tx")
        if (tx.kind, tx.pub_hex, tx.net_addr) != (
                entry["kind"], entry["pub"], entry["addr"]):
            # net_addr included: it is inside the subject-signed
            # message, and an unchecked entry['addr'] would let a
            # forged log redirect a validator's gossip address to an
            # attacker-chosen one (eclipse of that link)
            raise ValueError("membership log entry contradicts its tx")
        if tx.epoch > epoch or epoch - tx.epoch > PIPELINE_WINDOW:
            # pipelined transitions keep their submission-time stamp:
            # stamped at or before the epoch they apply FROM, within
            # the engine's queue bound
            raise ValueError(
                f"membership tx stamped epoch {tx.epoch}, applied at "
                f"epoch {epoch} (allowed window {PIPELINE_WINDOW})"
            )
        if not tx.verify():
            raise ValueError(
                f"membership tx for {tx.pub_hex[:18]}… has a bad "
                "subject signature"
            )
        if tx.kind == "join":
            if tx.pub_hex in participants:
                raise ValueError("membership log joins an existing member")
            participants[tx.pub_hex] = len(participants)
        else:
            cid = participants.get(tx.pub_hex)
            if cid is None or cid in retired:
                raise ValueError("membership log leaves a non-member")
            retired = retired + (cid,)
        epoch = entry["epoch"]
    return participants, retired


def verify_membership_chain(
    base_participants: Dict[str, int],
    base_retired: Tuple[int, ...],
    base_epoch: int,
    engine,
) -> Optional[str]:
    """Verify that ``engine``'s claimed peer set is exactly what its
    membership log derives from our trusted base.  Returns an error
    string (reject the snapshot) or None."""
    snap_epoch = int(getattr(engine, "epoch", 0))
    if snap_epoch < base_epoch:
        return (
            f"snapshot epoch {snap_epoch} is behind our epoch "
            f"{base_epoch}"
        )
    trunc = int(getattr(engine, "membership_base_epoch", 0) or 0)
    if base_epoch < trunc:
        # bounded membership_log: the snapshot truncated the chain
        # entries our trusted base would need.  Same contract as the
        # rolling event window's TooLate — bootstrap from a fresher
        # trusted base (updated bootstrap peers.json) instead.
        return (
            f"snapshot membership log is truncated at epoch {trunc}, "
            f"above our trusted base epoch {base_epoch} — cannot "
            "bridge the chain of custody"
        )
    log = list(getattr(engine, "membership_log", ()) or ())
    try:
        participants, retired = replay_log(
            base_participants, base_retired, log, base_epoch
        )
    except ValueError as e:
        return f"membership chain invalid: {e}"
    if len(log) and log[-1]["epoch"] != snap_epoch:
        return (
            f"membership log ends at epoch {log[-1]['epoch']} but the "
            f"snapshot claims epoch {snap_epoch}"
        )
    if not log and snap_epoch != base_epoch:
        return (
            f"snapshot claims epoch {snap_epoch} with no membership "
            "log to derive it"
        )
    if participants != engine.participants:
        return (
            "snapshot participant set does not match its own membership "
            f"chain ({len(engine.participants)} vs {len(participants)} "
            "entries)"
        )
    snap_retired = tuple(getattr(engine.cfg, "retired", ())) \
        if hasattr(engine, "cfg") else ()
    if tuple(sorted(retired)) != tuple(sorted(snap_retired)):
        return "snapshot retired set does not match its membership chain"
    return None

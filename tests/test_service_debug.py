"""Service endpoint coverage (ISSUE 2 satellites): /metrics exposition
on a live node, /debug/spans, loopback gating of /debug, NaN `seconds`
rejection, and /debug/trace tempdir retention.

The gating/NaN/retention tests drive ``Service._handle`` directly with
fake reader/writer pairs so a non-loopback peer can be simulated
without real remote sockets.
"""

import asyncio
import json
import os

from babble_tpu.crypto.keys import generate_key
from babble_tpu.net import InmemNetwork, Peer
from babble_tpu.node import Config, Node
from babble_tpu.proxy.inmem import InmemAppProxy
from babble_tpu.service.service import _MAX_TRACE_DIRS, Service


def _make_node():
    net = InmemNetwork()
    key = generate_key()
    t = net.transport()
    peers = [Peer(net_addr=t.local_addr(), pub_key_hex=key.pub_hex)]
    node = Node(Config.test_config(), key, peers, t, InmemAppProxy())
    node.init()
    return node


class _FakeReader:
    def __init__(self, request_line):
        self._lines = [request_line, b"\r\n"]

    async def readline(self):
        return self._lines.pop(0) if self._lines else b""


class _FakeWriter:
    def __init__(self, peer):
        self._peer = peer
        self.data = b""

    def get_extra_info(self, key):
        return self._peer

    def write(self, b):
        self.data += b

    async def drain(self):
        pass


async def _request(svc, path, peer=("127.0.0.1", 40000)):
    w = _FakeWriter(peer)
    await svc._handle(_FakeReader(f"GET {path} HTTP/1.1\r\n".encode()), w)
    head, _, body = w.data.partition(b"\r\n\r\n")
    status = head.split(b"\r\n")[0].split(b" ", 1)[1].decode()
    return status, body


# ----------------------------------------------------------------------
# /metrics + /debug/spans (the tentpole surface)


def test_metrics_endpoint_on_live_node():
    """Acceptance criterion: /metrics answers Prometheus text with >= 20
    series including the consensus-phase and gossip-RTT histograms,
    while /Stats keeps the reference schema untouched."""
    import urllib.request

    async def go():
        node = _make_node()
        svc = Service("127.0.0.1:0", node)
        await svc.start()
        base = f"http://{svc.bind_addr}"
        loop = asyncio.get_running_loop()

        def get(url):
            with urllib.request.urlopen(url, timeout=10) as r:
                return r.status, dict(r.headers), r.read()

        st, headers, body = await loop.run_in_executor(
            None, get, base + "/metrics"
        )
        assert st == 200
        assert headers["Content-Type"].startswith("text/plain")
        text = body.decode()
        series = [ln for ln in text.splitlines()
                  if ln and not ln.startswith("#")]
        assert len(series) >= 20, f"only {len(series)} series"
        assert "babble_consensus_phase_seconds_bucket" in text
        assert "babble_gossip_rtt_seconds_bucket" in text
        assert "babble_sync_requests_total" in text
        # /Stats stays byte-compatible with the reference key schema
        st, _, body = await loop.run_in_executor(None, get, base + "/Stats")
        stats = json.loads(body)
        for k in ("last_consensus_round", "consensus_events", "sync_rate",
                  "events_per_second", "transaction_pool", "id"):
            assert k in stats, k
        await svc.close()
        await node.shutdown()

    asyncio.run(go())


def test_debug_spans_endpoint():
    async def go():
        node = _make_node()
        with node.tracer.span("gossip", peer="x"):
            node.tracer.record("sync_apply", 0.002, events=3)
        svc = Service("127.0.0.1:0", node)
        status, body = await _request(svc, "/debug/spans")
        assert status == "200 OK"
        dump = json.loads(body)
        assert dump["dropped"] == 0
        (tree,) = dump["trees"]
        assert tree["name"] == "gossip"
        assert [c["name"] for c in tree["children"]] == ["sync_apply"]
        await node.shutdown()

    asyncio.run(go())


# ----------------------------------------------------------------------
# /debug gating + parameter validation (ISSUE 2 satellite)


def test_debug_is_loopback_only_by_default():
    async def go():
        node = _make_node()
        svc = Service("127.0.0.1:0", node)
        for path in ("/debug/stack", "/debug/spans"):
            status, body = await _request(
                svc, path, peer=("10.1.2.3", 5555)
            )
            assert status == "403 Forbidden", (path, status)
            assert b"loopback" in body
        # an absent peername (unix-socket-ish) is NOT local
        status, _ = await _request(svc, "/debug/stack", peer=None)
        assert status == "403 Forbidden"
        # loopback callers pass
        status, _ = await _request(svc, "/debug/stack")
        assert status == "200 OK"
        # /metrics and /Stats are read-only scrape surfaces: not gated
        status, _ = await _request(svc, "/metrics", peer=("10.1.2.3", 1))
        assert status == "200 OK"
        await node.shutdown()

    asyncio.run(go())


def test_allow_remote_debug_opens_the_gate():
    async def go():
        node = _make_node()
        svc = Service("127.0.0.1:0", node, allow_remote_debug=True)
        status, _ = await _request(
            svc, "/debug/stack", peer=("10.1.2.3", 5555)
        )
        assert status == "200 OK"
        await node.shutdown()

    asyncio.run(go())


def test_debug_rejects_nan_and_garbage_seconds():
    async def go():
        node = _make_node()
        svc = Service("127.0.0.1:0", node)
        # an EMPTY seconds= is dropped by parse_qs and falls back to
        # the default — only NaN/unparsable values are rejected
        for q in ("nan", "NaN", "abc"):
            status, body = await _request(
                svc, f"/debug/profile?seconds={q}"
            )
            assert status == "400 Bad Request", (q, status)
            assert b"bad seconds" in body
        await node.shutdown()

    asyncio.run(go())


def test_trace_tempdir_retention(monkeypatch):
    """Repeated /debug/trace calls must not accumulate unbounded disk:
    only the newest _MAX_TRACE_DIRS tempdirs survive, older ones are
    deleted from disk."""
    import jax

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)

    async def go():
        node = _make_node()
        svc = Service("127.0.0.1:0", node)
        dirs = []
        for _ in range(_MAX_TRACE_DIRS + 3):
            status, body = await _request(svc, "/debug/trace?seconds=0.1")
            assert status == "200 OK", status
            dirs.append(json.loads(body)["trace_dir"])
        assert len(svc._trace_dirs) == _MAX_TRACE_DIRS
        survivors = dirs[-_MAX_TRACE_DIRS:]
        assert svc._trace_dirs == survivors
        for d in survivors:
            assert os.path.isdir(d), d
        for d in dirs[:-_MAX_TRACE_DIRS]:
            assert not os.path.exists(d), d
        await svc.close()   # close() reaps the survivors too
        for d in survivors:
            assert not os.path.exists(d), d
        await node.shutdown()

    asyncio.run(go())

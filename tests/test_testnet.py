"""Fleet-ops tests (reference docker/scripts workflow: build-conf ->
run-testnet -> bombard -> watch)."""

import asyncio
import os

import pytest

from babble_tpu import testnet as tn


def test_build_conf_is_idempotent(tmp_path):
    base = str(tmp_path / "net")
    dirs = tn.build_conf(base, 3)
    keys1 = [open(os.path.join(d, "priv_key.pem")).read() for d in dirs]
    # second run must keep existing keys (a fleet's identity is its keys)
    tn.build_conf(base, 3)
    keys2 = [open(os.path.join(d, "priv_key.pem")).read() for d in dirs]
    assert keys1 == keys2
    # all nodes share one peers.json naming every gossip address
    import json

    peers = json.load(open(os.path.join(dirs[0], "peers.json")))
    assert len(peers) == 3
    assert json.load(open(os.path.join(dirs[1], "peers.json"))) == peers


@pytest.mark.slow
def test_testnet_end_to_end(tmp_path):
    """4-node fleet + dummy apps + bombard + watch — the reference demo
    workflow (docker/makefile) on one host, no containers."""
    ports = tn.PortLayout(gossip=22000, submit=23000, commit=24000,
                          service=25000)
    runner = tn.TestnetRunner(
        str(tmp_path / "net"), 4, heartbeat_ms=20, ports=ports,
    )
    with runner:
        import socket
        import time

        # wait for the whole fleet to accept transactions (JAX import
        # dominates node boot, ~15s)
        deadline = time.time() + 180
        for i in range(4):
            addr = ports.of(i)["submit"]
            host, port = addr.rsplit(":", 1)
            while True:
                try:
                    socket.create_connection((host, int(port)), 0.5).close()
                    break
                except OSError:
                    if time.time() > deadline:
                        raise RuntimeError(f"node {i} never came up")
                    time.sleep(0.5)

        sent = asyncio.run(
            tn.bombard(4, rate=100.0, duration=6.0, ports=ports)
        )
        assert sent >= 10

        # watch until every node has committed everything that was sent
        import time

        deadline = time.time() + 180
        while time.time() < deadline:
            rows = tn.watch_once(4, ports)
            done = [
                r for r in rows
                if "error" not in r and int(r["consensus_transactions"]) >= sent
            ]
            if len(done) == 4:
                break
            time.sleep(1.0)
        else:
            raise AssertionError(f"fleet never converged: {rows}")

        table = tn.format_stats(rows)
        assert "consensus_events" in table

        # all apps eventually wrote every tx, in identical order
        def read_logs():
            out = []
            for i in range(4):
                p = tmp_path / "net" / f"node{i}" / "messages.txt"
                out.append(p.read_text().splitlines() if p.exists() else [])
            return out

        deadline = time.time() + 120
        while time.time() < deadline:
            logs = read_logs()
            if min(len(l) for l in logs) >= sent:
                break
            time.sleep(1.0)
        k = min(len(l) for l in logs)
        assert k >= sent, f"app logs lag: {[len(l) for l in logs]} < {sent}"
        for l in logs[1:]:
            assert l[:k] == logs[0][:k]

"""Babble-side socket AppProxy (reference proxy/app/socket_app_proxy.go).

Runs a JSON-RPC server exposing ``Babble.SubmitTx`` (app → node submit
queue) and a client calling ``State.CommitTx`` on the app for every
consensus transaction, requiring an ack.
"""

from __future__ import annotations

import asyncio

from .jsonrpc import JsonRpcClient, JsonRpcServer, b64d, b64e


class SocketAppProxy:
    def __init__(self, client_addr: str, bind_addr: str, timeout: float = 5.0):
        """client_addr: the app's State server; bind_addr: where we listen
        for the app's SubmitTx calls."""
        self.submit_queue: "asyncio.Queue[bytes]" = asyncio.Queue()
        self.server = JsonRpcServer(bind_addr)
        self.server.register("Babble.SubmitTx", self._submit_tx)
        self.client = JsonRpcClient(client_addr, timeout)

    async def start(self) -> None:
        await self.server.start()

    @property
    def bind_addr(self) -> str:
        return self.server.bind_addr

    async def _submit_tx(self, tx_b64: str):
        await self.submit_queue.put(b64d(tx_b64))
        return True

    async def commit_tx(self, tx: bytes) -> None:
        ack = await self.client.call("State.CommitTx", b64e(tx))
        if ack is not True:
            raise RuntimeError(f"app failed to ack committed tx: {ack!r}")

    async def close(self) -> None:
        await self.server.close()
        await self.client.close()

"""CLI (reference cmd/main.go:39-260): keygen, run, sim.

- ``keygen``  — print (or write to a datadir) a PEM keypair.
- ``run``     — boot a node: key + peers from the datadir, TCP transport,
  socket or inmem proxy, /Stats service, then the gossip loop.
- ``sim``     — generate a random gossip DAG and run batch consensus on
  the device pipeline (no networking; the benchmark path).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time


def cmd_keygen(args) -> int:
    from .crypto.keys import PemKeyFile, generate_key, pem_dump

    key = generate_key()
    if args.datadir:
        pem = PemKeyFile(args.datadir)
        if pem.exists():
            print(f"key already exists in {args.datadir}", file=sys.stderr)
            return 1
        pem.write(key)
        print(f"wrote {pem.path}")
    priv, pub = pem_dump(key)
    print(f"PublicKey:\n{pub}")
    if not args.datadir:
        print(f"PrivateKey:\n{priv}")
    return 0


def _parse_fork_caps(spec: str, flag: str = "--fork_caps"):
    """'e,s,r' -> (e, s, r), failing at the flag instead of as a bare
    IndexError inside the consensus loop."""
    if not spec:
        return None
    parts = spec.split(",")
    if len(parts) != 3:
        raise SystemExit(
            f"{flag} wants exactly 'e,s,r' (got {spec!r})"
        )
    try:
        caps = tuple(int(x) for x in parts)
    except ValueError:
        raise SystemExit(f"{flag} values must be integers: {spec!r}")
    if any(v <= 0 for v in caps):
        raise SystemExit(f"{flag} values must be positive: {spec!r}")
    return caps


async def _run_node(args) -> int:
    import os

    # Persistent jit cache, shared by every node under one testnet root:
    # live gossip produces a spread of bucketed batch shapes, and without
    # the cache each (kpad, tpad, bpad) combination costs a fresh multi-
    # second XLA compile on every node, every run — a compile storm that
    # dominates fleet throughput.
    cache_dir = ""
    if args.jax_cache != "off":
        from .ops import aot

        cache_dir = args.jax_cache or os.path.join(
            os.path.abspath(args.datadir), "jax_cache"
        )
        # one surface for the cache flags (ops/aot.py): persistent XLA
        # cache + compile-event listeners; the AOT shape manifest lives
        # in the same directory and Node prewarms from it at boot
        aot.configure(cache_dir)

    from .crypto.keys import PemKeyFile
    from .net.peers import JSONPeers
    from .net.tcp_transport import new_tcp_transport
    from .node.config import Config
    from .node.node import Node
    from .proxy.inmem import InmemAppProxy
    from .proxy.socket_app import SocketAppProxy
    from .service.service import Service

    key = PemKeyFile(args.datadir).read()
    peers = JSONPeers(args.datadir).peers()
    # membership plane: a JOINER's epoch-0 validator set is the
    # founders' peers.json, while its own address rides only the
    # gossip book — it observes until its signed join tx commits
    bootstrap_peers = None
    bp_path = getattr(args, "bootstrap_peers", "")
    if bp_path:
        from .net.peers import peers_from_file

        bootstrap_peers = peers_from_file(bp_path)

    engine = None
    ckpt_dir = getattr(args, "checkpoint_dir", "")
    if ckpt_dir and os.path.isdir(ckpt_dir):
        # corruption-tolerant restart: a rotten checkpoint degrades to
        # a fresh engine + WAL replay + gossip/fast-forward instead of
        # a dead node (the chaos plane's disk-rot scenario pins this)
        from .store import load_checkpoint_tolerant

        engine, ckpt_err = load_checkpoint_tolerant(ckpt_dir)
        if ckpt_err is not None:
            if not getattr(args, "wal_dir", ""):
                # without a WAL there is no mint floor and no seq
                # probe: booting a fresh root here would re-mint every
                # published seq and peers would read this identity as
                # an equivocator (the crash-recovery-amnesia defect) —
                # refuse instead of silently poisoning the fleet
                raise SystemExit(
                    f"checkpoint {ckpt_dir} is unreadable ({ckpt_err}) "
                    "and no --wal_dir is configured: a fresh boot would "
                    "re-mint published sequence numbers.  Configure "
                    "--wal_dir (recovery degrades safely through the "
                    "WAL + seq probe), restore the checkpoint, or "
                    "remove the directory to explicitly start over."
                )
            print(
                f"warning: checkpoint {ckpt_dir} unreadable ({ckpt_err}); "
                "starting fresh and recovering from the WAL",
                file=sys.stderr,
            )
    if engine is not None:
        from .store.checkpoint import engine_mode

        mode = engine_mode(engine)
        want = ("byzantine" if args.byzantine
                else getattr(args, "engine", "fused"))
        if mode != want:
            raise SystemExit(
                f"checkpoint {ckpt_dir} engine kind '{mode}' does not "
                f"match the configured engine '{want}'"
            )
        if mode == "byzantine":
            caps = _parse_fork_caps(getattr(args, "fork_caps", ""))
            if caps:
                # the checkpoint carries no capacity hints: re-apply the
                # pre-sizing or every resume pays the growth re-jits
                engine.pre_size(caps)
        elif mode == "wide":
            want_caps = _parse_fork_caps(getattr(args, "wide_caps", ""),
                                         flag="--wide_caps")
            have = (engine.cfg.e_cap, engine.cfg.s_cap, engine.cfg.r_cap)
            if want_caps and tuple(want_caps) != have:
                # wide capacities are fixed at boot; the snapshot's
                # shapes win on resume — say so instead of letting the
                # operator believe the flag took effect
                print(
                    f"warning: --wide_caps {want_caps} ignored — the "
                    f"resumed checkpoint's window capacities are {have} "
                    "and cannot change post-boot",
                    file=sys.stderr,
                )
        n_ev = (len(engine.dag.events) if mode == "byzantine"
                else engine.dag.n_events)
        print(f"resumed from checkpoint {ckpt_dir}: "
              f"{n_ev} events, "
              f"{engine.consensus_events_count()} in consensus order")

    conf = Config(
        heartbeat=args.heartbeat / 1000.0,
        tcp_timeout=args.tcp_timeout / 1000.0,
        cache_size=args.cache_size,
        consensus_interval=args.consensus_interval / 1000.0,
        pipeline=not getattr(args, "no_pipeline", False),
        gossip_fanout=getattr(args, "gossip_fanout", 1),
        gossip_inflight=getattr(args, "gossip_inflight", 4),
        gossip_eager=not getattr(args, "no_eager_gossip", False),
        coalesce_max=getattr(args, "coalesce_max", 1024),
        coalesce_latency=getattr(args, "coalesce_latency", 50) / 1000.0,
        mint_backpressure=getattr(args, "mint_backpressure", 0) or None,
        seq_window=args.seq_window or None,
        # 0 disables the inactivity policy (a silent peer then pins
        # eviction fleet-wide, the pre-PR-8 behavior); -1 = default
        inactive_rounds=(
            None if getattr(args, "inactive_rounds", -1) == 0
            else (getattr(args, "inactive_rounds", -1)
                  if getattr(args, "inactive_rounds", -1) > 0 else 32)
        ),
        ff_verify=not getattr(args, "no_ff_verify", False),
        anchor_interval=getattr(args, "anchor_interval", 2048),
        bootstrap_peers=bootstrap_peers,
        byzantine=args.byzantine,
        fork_k=args.fork_k,
        fork_caps=_parse_fork_caps(getattr(args, "fork_caps", "")),
        engine=getattr(args, "engine", "fused"),
        wide_caps=_parse_fork_caps(getattr(args, "wide_caps", ""),
                                   flag="--wide_caps"),
        wal_dir=getattr(args, "wal_dir", ""),
        wal_fsync=getattr(args, "wal_fsync", "batch"),
        kernel_class=getattr(args, "kernel_class", "auto"),
        # kernel working-set diet (ROADMAP item 4): both pins are
        # bit-parity-preserving — they select kernel math, not
        # semantics (bench.py diet runs the before/after arms)
        packed_votes=not getattr(args, "no_packed_votes", False),
        frontier=not getattr(args, "no_frontier", False),
        # AOT prewarm shares the jit-cache root: the shape manifest
        # sits beside the persistent XLA cache it replays into
        aot_dir=(
            "" if getattr(args, "no_aot_prewarm", False) else cache_dir
        ),
        # attribution plane (ISSUE 11)
        lineage=not getattr(args, "no_lineage", False),
        flight=not getattr(args, "no_flight", False),
        phase_probe=getattr(args, "phase_probe", False),
        commit_slo=getattr(args, "commit_slo", 1000) / 1000.0,
    )
    conf.logger.setLevel(args.log_level.upper())

    transport = await new_tcp_transport(
        args.node_addr, max_pool=args.max_pool,
        timeout=conf.tcp_timeout,
    )
    if getattr(args, "chaos_plan", ""):
        # self-injected faults for live fleets: every node wraps its TCP
        # transport in the same (plan, seed)-driven FaultyTransport the
        # in-memory scenario runner uses, deriving its own link identity
        # from the canonical peer order — no per-node flags needed
        # _chaos_wrap reads the wall clock BY DESIGN: live fleets map
        # plan ticks onto shared wall time (--chaos_epoch) so restarted
        # nodes rejoin the fault schedule in phase — the wall clock
        # drives only the injector's tick cursor, never event bodies
        # (those go through Core.now_ns)
        transport = _chaos_wrap(transport, args, key, peers)  # babble-lint: disable=consensus-nondeterminism
        print(f"chaos plan {args.chaos_plan} active "
              f"(seed {transport.injector.seed})", file=sys.stderr)

    if args.no_client:
        proxy = InmemAppProxy()
    else:
        proxy = SocketAppProxy(
            args.client_addr, args.proxy_addr,
            timeout=conf.tcp_timeout,
            submit_per_client=getattr(args, "submit_per_client", 1024),
            submit_total=getattr(args, "submit_total", 8192),
            submit_adaptive=getattr(args, "submit_adaptive", False),
        )
        await proxy.start()

    node = Node(conf, key, peers, transport, proxy, engine=engine)
    if engine is None:
        # Node.init is recovery-aware: it skips the root mint when WAL
        # replay already restored a head, and defers it while the seq
        # probe negotiates a skip-ahead with the fleet
        node.init()
    service = Service(args.service_addr, node,
                      allow_remote_debug=args.allow_remote_debug)
    await service.start()
    print(f"node {node.core.id} listening on {transport.local_addr()}, "
          f"stats on http://{service.bind_addr}/Stats, "
          f"metrics on http://{service.bind_addr}/metrics")

    saver = None
    if ckpt_dir:
        saver = asyncio.create_task(
            _checkpoint_loop(node, ckpt_dir, args.checkpoint_interval)
        )
    try:
        await node.run(gossip=True)
    except Exception:
        # crash post-mortem (ISSUE 11): an unhandled select-loop error
        # dumps the flight recorder's last-N-transitions narrative next
        # to the datadir before the process dies — the in-memory ring
        # would otherwise die with it
        _dump_flight_on_crash(node, args.datadir)
        raise
    finally:
        if saver is not None:
            saver.cancel()
        if ckpt_dir:
            await node.save_checkpoint(ckpt_dir)
        await service.close()
        await node.shutdown()
    return 0


def _dump_flight_on_crash(node, datadir: str) -> None:
    import os

    try:
        path = os.path.join(datadir, "flight-crash.json")
        with open(path, "w") as f:
            json.dump({"stats": node.flight.stats(),
                       "records": node.flight.dump()}, f, indent=1)
        print(f"flight recorder dumped to {path}", file=sys.stderr)
    except Exception as e:   # the dump must never mask the real crash
        print(f"flight dump failed: {e}", file=sys.stderr)


def _chaos_wrap(transport, args, key, peers):
    """Wrap a live node's transport in a FaultyTransport driven by the
    scenario (or bare fault-plan) JSON at --chaos_plan.  Ticks map to
    wall time through the scenario's tick_seconds; link identities are
    canonical participant ids, so every node in the fleet derives the
    same per-link fault streams from the shared seed."""
    import time

    from .chaos import FaultInjector, FaultPlan, FaultyTransport, Scenario
    from .net.peers import canonical_ids

    with open(args.chaos_plan) as f:
        spec = json.load(f)
    joiners = 0
    if "plan" in spec:
        sc = Scenario.from_dict(spec)
        plan, tick_seconds, seed = sc.plan, sc.tick_seconds, sc.seed
        joiners = sc.joiners
    else:
        plan, tick_seconds, seed = FaultPlan.from_dict(spec), 0.05, 0
    if getattr(args, "chaos_seed", None) is not None:
        seed = args.chaos_seed
    # Link identities: the fleet DRIVER's address map when provided
    # (--chaos_addrs, written by chaos run --live next to the scenario
    # JSON) — the only exact source once joiners exist, because it
    # names every scheduled joiner's address/index BEFORE the joiner's
    # transition commits, so founders apply link faults on
    # founder->joiner traffic too and multiple joiners cannot collide
    # on one index.  Without it, fall back to canonical ids over the
    # FOUNDING set (a joiner's peers.json carries its own address too,
    # and folding that key into the sort would renumber every
    # founder's per-link fault stream); extra address-book entries
    # take the joiner indices in address order — exact only for a
    # single joiner, so hand-rolled multi-joiner fleets should pass
    # --chaos_addrs.
    addrs_path = getattr(args, "chaos_addrs", "")
    bp_path = getattr(args, "bootstrap_peers", "")
    if bp_path:
        from .net.peers import peers_from_file

        founders = peers_from_file(bp_path)
    else:
        founders = peers
    if addrs_path:
        with open(addrs_path) as f:
            addr_index = {a: int(i) for a, i in json.load(f).items()}
        own = addr_index[transport.local_addr()]
    else:
        ids = canonical_ids(founders)
        addr_index = {p.net_addr: ids[p.pub_key_hex] for p in founders}
        extra = sorted(
            p.net_addr for p in peers if p.pub_key_hex not in ids
        )
        for j, addr in enumerate(extra):
            addr_index[addr] = len(founders) + j
        own = (ids[key.pub_hex] if key.pub_hex in ids
               else addr_index[transport.local_addr()])
    plan.validate(len(founders), joiners=joiners)
    # tick 0 is the FLEET's epoch, not this process's boot: a node
    # relaunched mid-run (crash/restart schedule) must rejoin the shared
    # timeline, or it would replay the plan's partition/byzantine
    # schedule out of phase with everyone else.  The fleet driver passes
    # --chaos_epoch (unix seconds) to every node for exactly this;
    # without it, boot time is the epoch (single-boot fleets).
    epoch = getattr(args, "chaos_epoch", None)
    if epoch is None:
        epoch = time.time()
    injector = FaultInjector(
        plan, seed,
        clock=lambda: (time.time() - epoch) / tick_seconds,
        # the token bucket's refill clock: elapsed ticks x tick_seconds
        # must equal elapsed wall seconds, or bandwidth caps refill at
        # the wrong rate whenever the scenario stretches its timeline
        tick_seconds=tick_seconds,
    )
    return FaultyTransport(
        transport, injector, own, addr_index,
        # the forge_snapshot actor needs its own participant key to
        # re-sign the doctored proof — without it the mode would be a
        # silent no-op in live fleets
        forge_key=(key if injector.is_snapshot_forger(own) else None),
    )


async def _checkpoint_loop(node, ckpt_dir: str, interval: float) -> None:
    while True:
        await asyncio.sleep(interval)
        try:
            await node.save_checkpoint(ckpt_dir)
        except Exception as e:
            print(f"checkpoint failed: {e}", file=sys.stderr)


def cmd_run(args) -> int:
    try:
        return asyncio.run(_run_node(args))
    except KeyboardInterrupt:
        return 0


def cmd_sim(args) -> int:
    import functools

    import jax
    import numpy as np

    from .ops.state import DagConfig, init_state
    from .parallel.sharded import consensus_step_impl
    from .sim.arrays import batch_from_arrays, random_gossip_arrays

    t0 = time.perf_counter()
    dag = random_gossip_arrays(args.nodes, args.events, seed=args.seed)
    batch = batch_from_arrays(dag)
    cfg = DagConfig(
        n=args.nodes, e_cap=args.events,
        s_cap=max(64, dag.max_chain + 1), r_cap=args.rounds,
    )
    print(f"host build: {time.perf_counter()-t0:.2f}s "
          f"(native={__import__('babble_tpu.native', fromlist=['x']).available()})",
          file=sys.stderr)
    step = jax.jit(functools.partial(consensus_step_impl, cfg, "fast"))
    t0 = time.perf_counter()
    out = step(init_state(cfg), batch)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    if args.profile:
        # xprof trace of the steady-state step (reference piggy-backs Go
        # pprof on its HTTP listener, cmd/main.go:26; the TPU equivalent
        # is a jax profiler trace viewable in tensorboard/xprof)
        with jax.profiler.trace(args.profile):
            out = step(init_state(cfg), batch)
            jax.block_until_ready(out)
        print(f"profile written to {args.profile}", file=sys.stderr)
    t0 = time.perf_counter()
    out = step(init_state(cfg), batch)
    jax.block_until_ready(out)
    run_s = time.perf_counter() - t0
    ordered = int(np.count_nonzero(np.asarray(out.rr)[: args.events] >= 0))
    print(json.dumps({
        "nodes": args.nodes,
        "events": args.events,
        "ordered": ordered,
        "last_consensus_round": int(out.lcr),
        "max_round": int(out.max_round),
        "compile_s": round(compile_s, 3),
        "run_s": round(run_s, 4),
        "events_per_sec": round(ordered / run_s, 1) if run_s > 0 else None,
    }))
    return 0


async def _run_dummy(args) -> int:
    from .proxy.dummy import DummySocketClient

    client = DummySocketClient(args.node_addr, args.listen, log_path=args.log)
    await client.start()
    if not args.quiet:
        print(f"dummy client: submit -> {args.node_addr}, "
              f"commits <- {client.proxy.bind_addr}; type messages:")

    loop = asyncio.get_running_loop()
    last_seen = 0

    async def print_commits():
        nonlocal last_seen
        while True:
            await asyncio.sleep(0.3)
            msgs = client.state.get_messages()
            for m in msgs[last_seen:]:
                print(f"<< {m}")
            last_seen = len(msgs)

    printer = None if args.quiet else asyncio.create_task(print_commits())
    try:
        if args.quiet:
            await asyncio.Event().wait()  # serve until killed
        else:
            while True:
                line = await loop.run_in_executor(None, sys.stdin.readline)
                if not line:
                    break
                line = line.strip()
                if line:
                    await client.submit_tx(line.encode())
    finally:
        if printer is not None:
            printer.cancel()
        await client.close()
    return 0


def cmd_dummy(args) -> int:
    """Interactive chat client (reference cmd/dummy_client/main.go)."""
    try:
        return asyncio.run(_run_dummy(args))
    except KeyboardInterrupt:
        return 0


def cmd_testnet(args) -> int:
    from . import testnet as tn

    ports = tn.PortLayout(
        gossip=args.base_port, submit=args.base_port + 1000,
        commit=args.base_port + 2000, service=args.base_port + 3000,
    )
    if args.testnet_cmd == "conf":
        dirs = tn.build_conf(args.dir, args.n, ports, overwrite=args.overwrite)
        print(f"wrote {len(dirs)} node configs under {args.dir}")
        return 0
    if args.testnet_cmd == "watch":
        while True:
            print("\x1b[2J\x1b[H" + tn.format_stats(
                tn.watch_once(args.n, ports)))
            if args.once:
                return 0
            time.sleep(args.interval)
    if args.testnet_cmd in ("health", "trace"):
        # the read-only observability sweeps share the fleet helpers:
        # a same-host testnet is just a HostLayout of explicit
        # host:service_port entries
        from . import fleet as fl

        layout = fl.HostLayout(
            [ports.of(i)["service"] for i in range(args.n)]
        )
        if args.testnet_cmd == "health":
            return _print_health(fl, layout, args.json)
        return _print_trace(fl, layout, args.txid, args.json)
    if args.testnet_cmd == "bombard":
        if getattr(args, "clients", 1) > 1:
            # many-client harness: per-connection admission identities,
            # structured-overloaded backoff, shed/error accounting
            counts = asyncio.run(tn.bombard_many(
                args.n, clients=args.clients, rate=args.rate,
                duration=args.duration, ports=ports,
                batch=getattr(args, "batch", 1)))
            print(f"submitted {counts['sent']} transactions "
                  f"({counts['shed']} shed, {counts['errors']} errors, "
                  f"{counts['clients']} clients)")
            return 0
        sent = asyncio.run(
            tn.bombard(args.n, args.rate, args.duration, ports))
        print(f"submitted {sent} transactions")
        return 0
    if args.testnet_cmd == "run":
        runner = tn.TestnetRunner(
            args.dir, args.n, heartbeat_ms=args.heartbeat,
            with_clients=not args.no_clients, ports=ports,
        )
        runner.start()
        print(f"{args.n} nodes up; /Stats at "
              f"http://127.0.0.1:{args.base_port + 3000}..{args.base_port + 3000 + args.n - 1}"
              f"; ctrl-C to stop")
        try:
            while True:
                time.sleep(args.interval)
                print(tn.format_stats(tn.watch_once(args.n, ports)))
        except KeyboardInterrupt:
            pass
        finally:
            runner.stop()
        return 0
    raise SystemExit(f"unknown testnet subcommand {args.testnet_cmd}")


def _print_health(fl, layout, as_json: bool) -> int:
    """One /healthz sweep rendered as the fleet table (or JSON).  Exit
    1 when any node is unreachable, degraded, or the fleet diverges —
    a health verb that always exits 0 is a decoration."""
    rows = fl.health_hosts(layout)
    divergence = fl.health_divergence(rows)
    if as_json:
        print(json.dumps({"nodes": rows, "divergence": divergence},
                         indent=1))
    else:
        print(fl.format_health(rows, divergence))
    ok = (
        all("health" in r for r in rows)
        and all(r["health"].get("status") == "ok" for r in rows)
        and not any(d["severity"] == "error" for d in divergence)
    )
    return 0 if ok else 1


def _print_trace(fl, layout, txid: str, as_json: bool) -> int:
    """Stitch one tx's cross-node lineage; exit 1 when nothing was
    found (wrong txid, lineage disabled, or the ledgers rolled off)."""
    from .obs.lineage import format_trace

    st = fl.trace_tx(layout, txid)
    if as_json:
        print(json.dumps(st, indent=1))
    else:
        if st["errors"]:
            for e in st["errors"]:
                print(f"{e['host']}: {e['kind']}: {e['error']}",
                      file=sys.stderr)
        print(format_trace(st))
    return 0 if st["timeline"] else 1


def cmd_fleet(args) -> int:
    from . import fleet as fl
    from . import testnet as tn

    with open(args.hosts) as f:
        hosts = [ln.strip() for ln in f if ln.strip()]
    if not hosts:
        raise SystemExit(f"{args.hosts} lists no hosts")
    if getattr(args, "rate", 1.0) <= 0:
        raise SystemExit("--rate must be positive")
    layout = fl.HostLayout(
        hosts, gossip_port=args.gossip_port, submit_port=args.submit_port,
        commit_port=args.commit_port, service_port=args.service_port,
    )
    if (layout.explicit_service_ports()
            and args.fleet_cmd not in ("watch", "scrape", "trace",
                                       "health")):
        # 'host:port' entries name SERVICE endpoints; conf/bombard
        # would resolve every node to one shared default gossip/submit
        # port on the same host and silently misroute
        raise SystemExit(
            "host:port entries are only valid for the read-only "
            f"sweeps (watch/scrape/trace/health), not '{args.fleet_cmd}'"
            " — list bare hosts and use the port flags instead"
        )
    if args.fleet_cmd == "conf":
        dirs = fl.build_fleet_conf(
            __import__("os").path.join(args.dir, "conf"), layout
        )
        scripts = fl.write_deploy_scripts(args.dir, layout)
        print(f"wrote {len(dirs)} node configs + "
              f"{len(scripts)} deploy files under {args.dir}")
        return 0
    if args.fleet_cmd == "watch":
        while True:
            print("\x1b[2J\x1b[H" + tn.format_stats(fl.watch_hosts(layout)))
            if args.once:
                return 0
            time.sleep(args.interval)
    if args.fleet_cmd == "bombard":
        sent = asyncio.run(
            fl.bombard_hosts(layout, args.rate, args.duration))
        print(f"submitted {sent} transactions")
        return 0
    if args.fleet_cmd == "health":
        return _print_health(fl, layout, args.json)
    if args.fleet_cmd == "trace":
        return _print_trace(fl, layout, args.txid, args.json)
    if args.fleet_cmd == "scrape":
        rows = fl.scrape_hosts(layout)
        if getattr(args, "rollup", False):
            rollup = fl.rollup_metrics(rows)
            # digest-anchor divergence comes from /healthz (a hash
            # cannot be a metric sample); best-effort — rollup output
            # must not require every node to serve the health surface
            try:
                hrows = fl.health_hosts(layout)
                # epoch divergence is already covered by the
                # babble_epoch series check above
                rollup["divergence"].extend(
                    d for d in fl.health_divergence(hrows)
                    if d["kind"] == "digest"
                )
            except Exception as e:
                rollup["health_error"] = str(e)
            if args.json:
                print(json.dumps(rollup, indent=1))
            else:
                print(fl.format_rollup(rollup))
            # a diverged fleet must fail the sweep the same way fleet
            # health would — CI scripted on this exit code must not
            # see green over a split committed history
            diverged = any(
                d.get("severity") == "error"
                for d in rollup["divergence"]
            )
            return 0 if not rollup["unparsed"] and not diverged else 1
        if getattr(args, "spans", False):
            # merge the span sweep into the metrics rows; span output is
            # structured (trees), so this mode is always JSON.  A
            # loopback-gated host's spans row carries kind='gated' —
            # expected policy, so it does not flip the exit code the way
            # a missing /metrics blob does.
            for row, srow in zip(rows, fl.scrape_spans(layout)):
                if "spans" in srow:
                    row["spans"] = srow["spans"]
                else:
                    row["spans_kind"] = srow["kind"]
                    row["spans_error"] = srow["error"]
            print(json.dumps(rows, indent=1))
            ok = all(
                "metrics" in r
                and ("spans" in r or r.get("spans_kind") == "gated")
                for r in rows
            )
            return 0 if ok else 1
        if args.json:
            print(json.dumps(rows, indent=1))
        else:
            # one exposition blob per host, comment-separated so the
            # output stays valid Prometheus text; failures go to stderr
            # and flip the exit code (a silent half-sweep reads as a
            # healthy fleet)
            for row in rows:
                if "metrics" in row:
                    print(f"# ==== {row['host']} ====")
                    print(row["metrics"], end="")
                else:
                    print(f"{row['host']}: {row['kind']}: {row['error']}",
                          file=sys.stderr)
        return 0 if all("metrics" in r for r in rows) else 1
    raise SystemExit(f"unknown fleet subcommand {args.fleet_cmd}")


def cmd_chaos(args) -> int:
    from .chaos import (
        CANNED,
        Scenario,
        canned_names,
        load_scenario,
        run_live,
        run_scenario,
    )

    if args.chaos_cmd == "list":
        for name in canned_names():
            sc = CANNED[name]
            print(f"{name}: {sc['nodes']} nodes, {sc['steps']} steps, "
                  f"engine={sc.get('engine', 'fused')}, "
                  f"invariants={','.join(sc['invariants'])}")
        return 0
    if args.chaos_cmd == "show":
        print(json.dumps(load_scenario(args.scenario).to_dict(), indent=1))
        return 0
    if args.chaos_cmd == "run":
        sc = load_scenario(args.scenario)
        overrides = {}
        if args.seed is not None:
            overrides["seed"] = args.seed
        if args.steps is not None:
            overrides["steps"] = args.steps
        if args.nodes is not None:
            overrides["nodes"] = args.nodes
        if overrides:
            sc = Scenario.from_dict({**sc.to_dict(), **overrides})
        if args.live:
            report = run_live(sc, args.dir)
            print(json.dumps(report, indent=1))
            return 0 if report.get("advanced") else 1
        result = run_scenario(sc)
        if args.json:
            print(json.dumps(result.to_dict(), indent=1))
        else:
            print(f"scenario {result.name} seed={result.seed} "
                  f"steps={result.steps}")
            print(f"fingerprint {result.fingerprint()}")
            print(f"faults injected: {result.fault_counts or '{}'}")
            print("consensus events: " + ", ".join(
                f"node{i}={c}"
                for i, c in sorted(result.consensus_counts_final.items())
            ))
            print(result.report.format())
        if not result.report.ok:
            print("CHAOS RUN FAILED: invariant violation(s) above",
                  file=sys.stderr)
        return 0 if result.report.ok else 1
    raise SystemExit(f"unknown chaos subcommand {args.chaos_cmd}")


def _cmd_lint_fallback(_args) -> int:
    # unreachable while main()'s `lint` interception exists (argparse
    # never sees the verb); calls the analysis CLI directly — never
    # back through main() — so it cannot recurse if that ever changes
    from .analysis.cli import main as lint_main

    return lint_main([])


def main(argv=None) -> int:
    import os

    # `lint` forwards verbatim BEFORE argparse sees the tail: REMAINDER
    # cannot capture a leading option (`lint --json ...`), and the
    # analysis CLI owns its whole surface anyway.  Also skips the jax
    # platform plumbing below — the linter must run without jax.
    raw = sys.argv[1:] if argv is None else list(argv)
    if raw and raw[0] == "lint":
        from .analysis.cli import main as lint_main

        return lint_main(raw[1:])

    # Sitecustomize-registered accelerator plugins can take precedence over
    # JAX_PLATFORMS; this forces the platform through jax.config before any
    # backend initializes (fleets of local nodes must share the CPU, not
    # fight over one accelerator).
    plat = os.environ.get("BABBLE_JAX_PLATFORM", "")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)

    p = argparse.ArgumentParser(prog="babble-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    kg = sub.add_parser("keygen", help="generate an ECDSA P-256 keypair")
    kg.add_argument("--datadir", default="", help="write priv_key.pem here")
    kg.set_defaults(fn=cmd_keygen)

    rn = sub.add_parser("run", help="run a consensus node")
    rn.add_argument("--datadir", default=".",
                    help="dir with priv_key.pem and peers.json")
    rn.add_argument("--node_addr", default="127.0.0.1:1337")
    rn.add_argument("--no_client", action="store_true",
                    help="use an in-memory app proxy instead of sockets")
    rn.add_argument("--proxy_addr", default="127.0.0.1:1338",
                    help="where we listen for the app's SubmitTx")
    rn.add_argument("--client_addr", default="127.0.0.1:1339",
                    help="the app's CommitTx server")
    rn.add_argument("--service_addr", default="127.0.0.1:8000")
    rn.add_argument("--allow_remote_debug", action="store_true",
                    help="serve /debug/* to non-loopback callers "
                         "(default: loopback only)")
    rn.add_argument("--log_level", default="info")
    rn.add_argument("--heartbeat", type=int, default=1000, help="ms")
    rn.add_argument("--max_pool", type=int, default=2)
    rn.add_argument("--tcp_timeout", type=int, default=1000, help="ms")
    rn.add_argument("--cache_size", type=int, default=500)
    rn.add_argument("--no_pipeline", action="store_true",
                    help="disable pipelined gossip (speculative push); "
                         "restores the lockstep pull exchange")
    rn.add_argument("--gossip_fanout", type=int, default=1,
                    help="peers gossiped per heartbeat tick")
    rn.add_argument("--gossip_inflight", type=int, default=4,
                    help="max concurrent outbound gossip exchanges")
    rn.add_argument("--no_eager_gossip", action="store_true",
                    help="don't launch the next gossip immediately when "
                         "one finishes with txs pooled")
    rn.add_argument("--coalesce_max", type=int, default=1024,
                    help="max client txs coalesced into one event")
    rn.add_argument("--coalesce_latency", type=int, default=50,
                    help="ms a pooled tx may wait before a self-parent "
                         "event is minted for it")
    rn.add_argument("--mint_backpressure", type=int, default=0,
                    help="pause deadline mints while undetermined "
                         "backlog exceeds this (0 = cache_size/4)")
    rn.add_argument("--submit_per_client", type=int, default=1024,
                    help="admission control: per-client submit queue cap")
    rn.add_argument("--submit_total", type=int, default=8192,
                    help="admission control: total submit queue cap")
    rn.add_argument("--submit_adaptive", action="store_true",
                    help="derive admission caps from the observed "
                         "commit drain rate (EWMA) instead of the "
                         "static caps")
    rn.add_argument("--bootstrap_peers", default="",
                    help="membership: path to the FOUNDING peers.json "
                         "when this node is a joiner (its own address "
                         "is only in the datadir peers.json; it "
                         "observes until its signed join tx commits)")
    rn.add_argument("--consensus_interval", type=int, default=0,
                    help="ms between consensus pipeline runs (0 = every sync)")
    rn.add_argument("--byzantine", action="store_true",
                    help="fork-aware live mode: accept + detect "
                         "equivocations instead of rejecting them")
    rn.add_argument("--fork_k", type=int, default=2,
                    help="branch slots per creator (fork budget K-1)")
    rn.add_argument("--fork_caps", default="",
                    help="pre-sized byzantine pipeline capacities "
                         "'e,s,r' (one jit shape at boot instead of "
                         "demand-driven growth recompiles)")
    rn.add_argument("--engine", default="fused",
                    choices=("fused", "wide"),
                    help="honest-mode engine: fused [E,N] coordinate "
                         "tensors, or the column-blocked rolling-window "
                         "wide engine (the 10k-participant layout)")
    rn.add_argument("--wide_caps", default="",
                    help="wide-engine window capacities 'e,s,r' "
                         "(fixed at boot; the engine compacts instead "
                         "of growing)")
    rn.add_argument("--seq_window", type=int, default=0,
                    help="per-creator rolling window (0 = cache_size)")
    rn.add_argument("--inactive_rounds", type=int, default=-1,
                    help="per-creator eviction: decided rounds of "
                         "silence before a creator's retained tail "
                         "evicts (its return then fast-forwards); "
                         "-1 = default 32, 0 = disabled")
    rn.add_argument("--no_ff_verify", action="store_true",
                    help="skip signed-state-proof verification on "
                         "fast-forward snapshots (trust any serving "
                         "peer — the pre-PR-8 model)")
    rn.add_argument("--anchor_interval", type=int, default=2048,
                    help="rolling attestation checkpoints: co-sign a "
                         "CommitDigest anchor with a peer quorum every "
                         "N commits (joiners verify deep fast-forwards "
                         "against it); 0 disables collection")
    rn.add_argument("--kernel_class", default="auto",
                    choices=("auto", "latency", "throughput"),
                    help="compiled-surface pin for the fused engine: "
                         "auto picks the small-batch latency kernel for "
                         "gossip-sized flushes, throughput for bulk")
    rn.add_argument("--no_packed_votes", action="store_true",
                    help="pin the pre-diet f32 vote tallies on the "
                         "fused latency kernel (bit-identical; the "
                         "packed popcount path is the default)")
    rn.add_argument("--no_frontier", action="store_true",
                    help="pin full-height fd scans in the windowed "
                         "order phase (bit-identical; the event-axis "
                         "frontier slice is the default)")
    rn.add_argument("--no_aot_prewarm", action="store_true",
                    help="skip AOT pre-compilation of recorded live-flush "
                         "shapes at boot (the persistent jit cache still "
                         "applies)")
    rn.add_argument("--jax_cache", default="",
                    help="jit cache dir ('' = <datadir>/../jax_cache, 'off' = disabled)")
    rn.add_argument("--checkpoint_dir", default="",
                    help="resume from + periodically checkpoint to this dir")
    rn.add_argument("--checkpoint_interval", type=float, default=30.0,
                    help="seconds between checkpoints")
    rn.add_argument("--wal_dir", default="",
                    help="per-event write-ahead log dir: restart replays "
                         "the tail on top of the newest checkpoint, so "
                         "the node resumes at its published head seq")
    rn.add_argument("--wal_fsync", default="batch",
                    help="WAL fsync policy: always | batch(n,ms) | off "
                         "(default batch = 64 appends / 50 ms)")
    rn.add_argument("--no_lineage", action="store_true",
                    help="disable commit-lineage tracing (per-tx/per-"
                         "event lifecycle ledgers behind /debug/lineage "
                         "and `fleet trace`)")
    rn.add_argument("--no_flight", action="store_true",
                    help="disable the flight recorder (state-transition "
                         "ring behind /debug/flight + crash dumps)")
    rn.add_argument("--phase_probe", action="store_true",
                    help="dispatch the fused latency flush as three "
                         "separately-timed sub-programs (ingest/fame/"
                         "order wall histograms; bit-identical results, "
                         "one host sync per phase — profiling posture)")
    rn.add_argument("--commit_slo", type=int, default=1000,
                    help="commit-latency SLO in ms for the /healthz "
                         "burn gauge")
    rn.add_argument("--chaos_plan", default="",
                    help="scenario/fault-plan JSON: wrap the transport "
                         "in a seeded FaultyTransport (chaos testing)")
    rn.add_argument("--chaos_seed", type=int, default=None,
                    help="override the chaos plan's seed")
    rn.add_argument("--chaos_epoch", type=float, default=None,
                    help="fleet-wide tick-0 (unix seconds) so restarted "
                         "nodes rejoin the shared chaos timeline "
                         "(default: this process's boot time)")
    rn.add_argument("--chaos_addrs", default="",
                    help="JSON map of gossip address -> scenario node "
                         "index (written by chaos run --live): the "
                         "exact link-identity source once joiners "
                         "exist; default derives identities from the "
                         "founding peer set")
    rn.set_defaults(fn=cmd_run)

    sm = sub.add_parser("sim", help="batch consensus over a generated DAG")
    sm.add_argument("--nodes", type=int, default=64)
    sm.add_argument("--events", type=int, default=16384)
    sm.add_argument("--rounds", type=int, default=256)
    sm.add_argument("--seed", type=int, default=7)
    sm.add_argument("--profile", default="",
                    help="write a jax profiler (xprof) trace to this dir")
    sm.set_defaults(fn=cmd_sim)

    dm = sub.add_parser("dummy", help="interactive chat client "
                        "(reference cmd/dummy_client)")
    dm.add_argument("--node_addr", default="127.0.0.1:1338",
                    help="the node's SubmitTx JSON-RPC server")
    dm.add_argument("--listen", default="127.0.0.1:1339",
                    help="where we serve the node's CommitTx calls")
    dm.add_argument("--log", default="messages.txt")
    dm.add_argument("--quiet", action="store_true",
                    help="no stdin/stdout chat; just serve commits")
    dm.set_defaults(fn=cmd_dummy)

    tnp = sub.add_parser("testnet", help="local fleet ops "
                         "(reference docker/scripts)")
    tsub = tnp.add_subparsers(dest="testnet_cmd", required=True)
    for name, hlp in (("conf", "write node datadirs + peers.json"),
                      ("run", "launch nodes + dummy apps"),
                      ("watch", "poll fleet /Stats"),
                      ("health", "one /healthz sweep + divergence table"),
                      ("trace", "stitch a tx's cross-node lineage"),
                      ("bombard", "flood random transactions")):
        sp = tsub.add_parser(name, help=hlp)
        sp.add_argument("--n", type=int, default=4)
        sp.add_argument("--dir", default="testnet-data")
        sp.add_argument("--base_port", type=int, default=12000)
        if name == "conf":
            sp.add_argument("--overwrite", action="store_true")
        if name == "health":
            sp.add_argument("--json", action="store_true")
        if name == "trace":
            sp.add_argument("txid", help="sha256 hex of the exact "
                                         "submitted tx bytes")
            sp.add_argument("--json", action="store_true")
        if name == "run":
            sp.add_argument("--heartbeat", type=int, default=10, help="ms")
            sp.add_argument("--no_clients", action="store_true")
            sp.add_argument("--interval", type=float, default=5.0)
        if name == "watch":
            sp.add_argument("--interval", type=float, default=2.0)
            sp.add_argument("--once", action="store_true")
        if name == "bombard":
            sp.add_argument("--rate", type=float, default=50.0, help="tx/s")
            sp.add_argument("--duration", type=float, default=10.0)
            sp.add_argument("--clients", type=int, default=1,
                            help=">1 uses the many-client harness "
                                 "(per-connection admission identities, "
                                 "overloaded-aware backoff)")
            sp.add_argument("--batch", type=int, default=1,
                            help="txs per Babble.SubmitTxBatch call "
                                 "(many-client harness only)")
        sp.set_defaults(fn=cmd_testnet)

    flp = sub.add_parser("fleet", help="multi-host fleet ops "
                         "(reference terraform/makefile + scripts)")
    fsub = flp.add_subparsers(dest="fleet_cmd", required=True)
    for name, hlp in (
        ("conf", "node datadirs + peers.json + ssh deploy scripts"),
        ("watch", "poll every host's /Stats"),
        ("scrape", "sweep every host's /metrics (Prometheus text)"),
        ("health", "sweep every host's /healthz into one fleet table "
                   "flagging epoch/lcr/digest divergence"),
        ("trace", "scrape + stitch one tx's cross-node commit lineage"),
        ("bombard", "flood transactions across the hosts"),
    ):
        sp = fsub.add_parser(name, help=hlp)
        sp.add_argument("--hosts", required=True,
                        help="file with one routable host address per "
                             "line ('host' or 'host:service_port' — the "
                             "latter for same-host fleets)")
        sp.add_argument("--dir", default="fleet-data")
        sp.add_argument("--gossip_port", type=int, default=1337)
        sp.add_argument("--submit_port", type=int, default=1338)
        sp.add_argument("--commit_port", type=int, default=1339)
        sp.add_argument("--service_port", type=int, default=8080)
        if name == "watch":
            sp.add_argument("--interval", type=float, default=2.0)
            sp.add_argument("--once", action="store_true")
        if name == "scrape":
            sp.add_argument("--json", action="store_true",
                            help="emit the sweep as a JSON row list "
                                 "instead of concatenated text")
            sp.add_argument("--spans", action="store_true",
                            help="also fetch each host's /debug/spans "
                                 "(loopback-gated hosts report kind="
                                 "'gated'); implies JSON output")
            sp.add_argument("--rollup", action="store_true",
                            help="aggregate per-node series into fleet "
                                 "sums/maxes with a divergence section "
                                 "(disagreeing babble_epoch / digest "
                                 "anchors render as warning rows)")
        if name == "health":
            sp.add_argument("--json", action="store_true")
        if name == "trace":
            sp.add_argument("txid", help="sha256 hex of the exact "
                                         "submitted tx bytes")
            sp.add_argument("--json", action="store_true")
        if name == "bombard":
            sp.add_argument("--rate", type=float, default=50.0, help="tx/s")
            sp.add_argument("--duration", type=float, default=10.0)
        sp.set_defaults(fn=cmd_fleet)

    chp = sub.add_parser("chaos", help="seeded fault injection + "
                         "consensus invariant checking (babble_tpu/chaos)")
    csub = chp.add_subparsers(dest="chaos_cmd", required=True)
    cl = csub.add_parser("list", help="list the canned scenarios")
    cl.set_defaults(fn=cmd_chaos)
    cs = csub.add_parser("show", help="print a scenario as JSON "
                         "(schema-by-example for custom plans)")
    cs.add_argument("scenario", help="canned name or scenario JSON path")
    cs.set_defaults(fn=cmd_chaos)
    cr = csub.add_parser("run", help="run a scenario and check its "
                         "invariants (exit 1 on violation)")
    cr.add_argument("scenario", help="canned name or scenario JSON path")
    cr.add_argument("--seed", type=int, default=None,
                    help="override the scenario seed (same seed = "
                         "bit-identical fault schedule + committed order)")
    cr.add_argument("--steps", type=int, default=None)
    cr.add_argument("--nodes", type=int, default=None)
    cr.add_argument("--json", action="store_true",
                    help="dump the full result (fault schedule, per-node "
                         "orders, invariant report) as JSON")
    cr.add_argument("--live", action="store_true",
                    help="run against a live subprocess testnet instead "
                         "of the deterministic in-memory cluster")
    cr.add_argument("--dir", default="chaos-data",
                    help="datadir for --live fleets")
    cr.set_defaults(fn=cmd_chaos)

    # `lint` never reaches argparse — the interception at the top of
    # main() forwards its whole tail verbatim (REMAINDER cannot capture
    # a leading option like `lint --json`).  Registered here only so the
    # verb appears in --help; the fn is a defensive fallback should the
    # interception ever move.
    lp = sub.add_parser(
        "lint",
        help="babble-lint static analysis (see python -m "
             "babble_tpu.analysis --help for the full surface)",
    )
    lp.set_defaults(fn=_cmd_lint_fallback)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

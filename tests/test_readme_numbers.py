"""The README perf table must not contradict the checked-in bench
artifacts (VERDICT r2 weak #1: the table said 242-247 ev/s while
BENCH_LIVE.json recorded 250.13).  Tolerances absorb run-to-run noise;
a real drift (stale table after a re-bench) fails loudly."""

import json
import os
import re

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _readme():
    with open(os.path.join(ROOT, "README.md")) as f:
        return f.read()


def test_live_fleet_number_matches_artifact():
    path = os.path.join(ROOT, "BENCH_LIVE.json")
    if not os.path.exists(path):
        pytest.skip("no live artifact")
    with open(path) as f:
        live = json.load(f)
    m = re.search(r"\|\s*live 4-node[^|]*\|\s*([\d.]+)\s*ev/s", _readme())
    assert m, "README live-fleet row missing"
    readme_eps = float(m.group(1))
    artifact = float(live["events_per_sec_gossip"])
    assert abs(readme_eps - artifact) / artifact < 0.10, (
        f"README says {readme_eps} ev/s, BENCH_LIVE.json says {artifact}"
    )


def test_rounds_to_fame_matches_artifact():
    path = os.path.join(ROOT, "BENCH_DETAIL.json")
    if not os.path.exists(path):
        pytest.skip("no detail artifact")
    with open(path) as f:
        detail = json.load(f)
    cfg10k = next((v for k, v in detail.items() if k.startswith("10000x")),
                  None)
    if cfg10k is None:
        pytest.skip("no 10k detail recorded")
    rtf = cfg10k["rounds_to_fame_structural"]
    assert rtf.get("0") == 2 or rtf.get(0) == 2, rtf
    assert "{0:2}" in _readme().replace(" ", "").replace("\n", ""), (
        "README 10k rounds-to-fame out of date"
    )


def test_ingress_numbers_match_artifact():
    """The ingress-plane row quotes ordered tx/s and the same-host
    baseline ratio; both must match BENCH_INGRESS.json (the ISSUE 6
    measured-not-hoped contract)."""
    path = os.path.join(ROOT, "BENCH_INGRESS.json")
    if not os.path.exists(path):
        pytest.skip("no ingress artifact")
    with open(path) as f:
        ing = json.load(f)
    m = re.search(r"\|\s*ingress plane[^|]*\|\s*([\d.]+)\s*ordered tx/s"
                  r"\s*\|\s*([\d.]+)x", _readme())
    assert m, "README ingress row missing"
    readme_tps, readme_ratio = float(m.group(1)), float(m.group(2))
    artifact = float(ing["txs_per_sec_loaded"])
    assert abs(readme_tps - artifact) / artifact < 0.10, (
        f"README says {readme_tps} tx/s, BENCH_INGRESS.json says {artifact}"
    )
    ratio = float(ing["txs_vs_same_host_baseline"])
    assert abs(readme_ratio - ratio) / ratio < 0.15, (
        f"README says {readme_ratio}x, artifact says {ratio}x"
    )


def test_live_loaded_number_matches_artifact():
    """The LOADED fleet number must be quoted and pinned too (VERDICT r4
    weak #4: quoting only the idle-gossip figure hides the honest
    number for a transaction-ordering platform)."""
    path = os.path.join(ROOT, "BENCH_LIVE.json")
    if not os.path.exists(path):
        pytest.skip("no live artifact")
    with open(path) as f:
        live = json.load(f)
    if "events_per_sec_loaded" not in live:
        pytest.skip("artifact has no loaded measurement")
    m = re.search(r"under 100 tx/s[^|]*\|\s*([\d.]+)\s*ev/s", _readme())
    assert m, "README loaded-fleet row missing"
    readme_eps = float(m.group(1))
    artifact = float(live["events_per_sec_loaded"])
    assert abs(readme_eps - artifact) / artifact < 0.10, (
        f"README says {readme_eps} ev/s loaded, BENCH_LIVE.json says "
        f"{artifact}"
    )


def test_diet_numbers_match_artifact():
    """The kernel-diet paragraph quotes the order-phase bytes drop;
    it must match BENCH_DIET.json (and the artifact must satisfy the
    ISSUE-14 acceptance gate it claims: >= 2x, parity ok)."""
    path = os.path.join(ROOT, "BENCH_DIET.json")
    if not os.path.exists(path):
        pytest.skip("no diet artifact")
    with open(path) as f:
        diet = json.load(f)
    m = re.search(r"drops \*\*([\d.]+)x\*\*", _readme())
    assert m, "README diet drop row missing"
    readme_x = float(m.group(1))
    artifact = float(diet["bytes_drop_x"]["order"])
    assert abs(readme_x - artifact) / artifact < 0.10, (
        f"README says {readme_x}x, BENCH_DIET.json says {artifact}x"
    )
    assert diet["order_bytes_drop_at_least_2x"] is True
    assert diet["parity"] == "ok"
